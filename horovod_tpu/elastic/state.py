"""Elastic training state — the Horovod ``State.commit()/restore()``
pattern, JAX-native.

The contract that makes in-process rescaling possible: everything a worker
needs to continue training after the world changes must exist as a HOST
(numpy) snapshot, because the rescale drops every live ``jax.Array`` along
with the old backends (`compat.clear_backends`). `ElasticState.commit`
takes that snapshot at clean boundaries (epoch ends, or every N steps);
`restore` rolls the live attributes back to it after a membership-change
interrupt; `sync` moves the freshest committed snapshot to (re)joining
members over ONE fused host-level broadcast — no checkpoint round-trip for
the common case (the checkpoint path stays as the fallback for members
whose process itself was restarted).

Cross-process-sharded state (ZeRO-1 optimizer shards, multi-host TP/FSDP)
cannot be device_get on any single process; `commit` snapshots those
leaves as this process's OWNED pieces (`ShardedLeaf`, the `save_sharded`
replica-0 dedup) so the commit stays communication-free, and
`gather_committed` reassembles them into dense host arrays — verified
piece-by-piece against the committing process's sha256 — at the
membership-change boundary, while every member of the departing
generation is still alive. A 3→2 ZeRO-1 shrink therefore keeps the
departing member's third of the optimizer state without any survivor
process restarting; layouts that genuinely cannot round-trip fail fast at
`elastic.run` entry (`validate_committable`).

`ElasticStateCallback` is the commit hook wired into the `Trainer` loop:
it tracks the trainer's state into the `ElasticState`, commits on the
chosen cadence, carries TCP heartbeats to the coordinator, and runs the
epoch-end **membership agreement** — the same allgather-agreement shape
`PreemptionCheckpointCallback` uses for signals — so every rank of a
generation tears down and re-rendezvouses at the SAME epoch boundary.
That lockstep is what lets `runtime.shutdown` complete its barrier
cleanly (a one-sided teardown makes the coordination service kill the
survivors; see `compat.distributed_shutdown_barrier`).
"""

from __future__ import annotations

import dataclasses
import hashlib
import signal

import jax
import numpy as np

from horovod_tpu import runtime
from horovod_tpu.elastic.coordinator import ElasticError
from horovod_tpu.parallel import collectives
from horovod_tpu.training.callbacks import Callback, agree_any

# What a control-plane call can throw when the coordinator is dying or
# racing teardown: socket errors, a mid-exchange close / error reply
# (ElasticError), or a torn JSON line (json.JSONDecodeError ⊂ ValueError).
CONTROL_PLANE_ERRORS = (OSError, ElasticError, ValueError)


class HostsUpdatedInterrupt(BaseException):
    """The world changed (a member joined/left/died): unwind out of fit(),
    restore committed state, re-rendezvous. BaseException so user-level
    ``except Exception`` blocks in training code cannot swallow it."""


class LeaveInterrupt(BaseException):
    """This member is leaving the fleet (planned departure: a scheduler
    SIGTERM, or the ``leave`` fault kind). `elastic.run` converts it into
    the 143 exit-status convention the supervisor classifies as clean."""


# Process-wide leave intent, STICKY across generations. The per-fit()
# handler below covers the common case, but a scheduler's SIGTERM can
# land in the rendezvous -> runtime-init -> trainer-build window where
# fit() hasn't installed it yet — and `jax.distributed.initialize`
# re-registers XLA's own preemption notifier over whatever handler was
# active, silently eating the signal. `elastic.run` therefore re-installs
# `signal_leave` right AFTER every runtime (re)build, and every
# membership agreement reads `leave_signaled()` alongside the
# callback-local flag, so a preemption can never be dropped on the floor
# (the failure mode is ugly: the victim trains on until the scheduler's
# grace escalation SIGKILLs it mid-collective, crashing the survivors).
_LEAVE_SIGNALED = False


def signal_leave(signum=None, frame=None) -> None:
    """SIGTERM handler (also callable directly): record leave intent."""
    global _LEAVE_SIGNALED
    _LEAVE_SIGNALED = True


def leave_signaled() -> bool:
    return _LEAVE_SIGNALED


def clear_leave_signal() -> None:
    """Reset after the leave is CONSUMED (the departing boundary ran, or
    `elastic.run` exited 143) — so in-process reuse (tests, nested runs)
    doesn't inherit a stale intent."""
    global _LEAVE_SIGNALED
    _LEAVE_SIGNALED = False


def progress_marker(epoch: int, step: int = 0) -> int:
    """Total order over committed progress: epochs dominate, steps break
    ties within an epoch (the every-N-steps commit cadence). Used to elect
    the rendezvous root — the member whose snapshot everyone adopts.
    Steps are clamped into the radix (`coordinator.PROGRESS_STEP_RADIX`),
    so a pathological beyond-radix epoch degrades to a tie within that
    epoch — it can never make a mid-epoch commit outrank the next epoch's
    start. The resume point itself travels as full-fidelity (epoch, step)
    ints; only this ORDERING key (and the journal's decompose of it) is
    radix-bounded."""
    from horovod_tpu.elastic.coordinator import PROGRESS_STEP_RADIX

    return int(epoch) * PROGRESS_STEP_RADIX + min(
        int(step), PROGRESS_STEP_RADIX - 1
    )


# --- per-shard commit for cross-process-sharded state -----------------------
#
# ZeRO-1/TP/FSDP layouts shard state ACROSS processes: no single process can
# `jax.device_get` those leaves, so the dense host snapshot `commit()` takes
# for replicated state is impossible. Instead each process snapshots exactly
# the pieces it OWNS — its addressable `replica_id == 0` shards, the same
# dedup `checkpoint.save_sharded` uses, so every piece of the global array
# is committed exactly once fleet-wide — as a `ShardedLeaf` carrying the
# global shape/dtype, the index specs, and a per-piece sha256. The commit
# stays communication-free (callable every epoch); the pieces are
# reassembled into dense host arrays by `ElasticState.gather_committed()`
# — one host-level object allgather (the KV transport) + the sharded-
# checkpoint slice-assembly logic (`checkpoint._assemble_global`) — which
# the elastic callback runs at the membership-change boundary while every
# member of the old generation, INCLUDING a clean leaver, is still alive.
# After the gather the snapshot is dense and the existing sync/broadcast
# machinery moves it like any other.


@dataclasses.dataclass
class ShardedLeaf:
    """One cross-process-sharded leaf's committed form: this process's
    owned pieces plus the metadata needed to reassemble the global array
    (and to prove, via per-piece sha256, that reassembly installed the
    committing process's exact bytes)."""

    shape: tuple
    dtype: str
    pieces: dict            # index spec -> np.ndarray (this process's share)
    digests: dict           # index spec -> sha256 hex of the piece's bytes

    @classmethod
    def snap(cls, leaf) -> "ShardedLeaf":
        from horovod_tpu import checkpoint

        pieces = {
            spec: np.ascontiguousarray(piece)
            for spec, piece in checkpoint.leaf_shard_pieces(leaf).items()
        }
        return cls(
            shape=tuple(leaf.shape),
            dtype=str(np.dtype(leaf.dtype)),
            pieces=pieces,
            digests={
                spec: hashlib.sha256(piece.tobytes()).hexdigest()
                for spec, piece in pieces.items()
            },
        )


def _is_cross_process(leaf) -> bool:
    """Whether a leaf is sharded across processes — the condition under
    which commit must snapshot pieces instead of a dense host copy.
    Module-level (not inlined) so single-process tests can patch the
    classification: real cross-process arrays cannot exist in one
    process."""
    from horovod_tpu import checkpoint

    return isinstance(leaf, jax.Array) and not checkpoint._host_syncable(leaf)


def _snap_leaf(leaf):
    """Commit-time snapshot of one leaf: dense host copy when any single
    process can hold it, `ShardedLeaf` pieces otherwise."""
    if _is_cross_process(leaf):
        return ShardedLeaf.snap(leaf)
    return jax.device_get(leaf)


def _has_sharded(tree) -> bool:
    return any(
        isinstance(l, ShardedLeaf) for l in jax.tree_util.tree_leaves(tree)
    )


def validate_committable(tree, where: str = "elastic.run") -> None:
    """Fail fast — with an actionable error — for layouts the per-shard
    commit genuinely cannot reassemble (strided shard indices), instead of
    crashing mid-training at the first commit or, worse, mid-rescale.
    Called by `ElasticStateCallback.on_train_begin`, i.e. at `elastic.run`
    entry of every generation, before any training step runs."""
    from horovod_tpu import checkpoint

    paths_and_leaves, _ = jax.tree_util.tree_flatten_with_path(tree)
    for path, leaf in paths_and_leaves:
        if not _is_cross_process(leaf):
            continue
        try:
            checkpoint.leaf_shard_pieces(leaf)
        except ValueError as e:
            raise RuntimeError(
                f"{where}: tracked state leaf "
                f"{jax.tree_util.keystr(path)!r} is sharded across "
                f"processes with a layout the elastic per-shard commit "
                f"cannot reassemble ({e}). Elastic continue-through-"
                "failure is unavailable for this layout — run under the "
                "plain supervised launcher (--max-restarts without "
                "--elastic) and rely on sharded checkpoints, or change "
                "the sharding to contiguous per-dimension slices."
            ) from None


class ElasticState:
    """Committed training state: named attributes (``state`` — typically a
    `TrainState` — plus ``epoch``/``step`` bookkeeping and any extra
    kwargs), snapshotted to host memory on ``commit()``.

    Attributes named at construction are the tracked set; assign to them
    freely between commits. After ``restore()`` array-valued attributes
    hold HOST (numpy) pytrees — `Trainer.install_state` puts them back on
    whatever mesh the new world built."""

    def __init__(self, state=None, epoch: int = 0, step: int = 0,
                 cursor: dict | None = None, **extra):
        # `cursor` is the durable data-stream cursor
        # (`Trainer.stream_cursor` — data/stream.py): committed and
        # synced like every tracked attribute, so a shrink/grow carries
        # the exact stream position to the next generation for free.
        self._tracked = ("state", "epoch", "step", "cursor", *extra)
        self.state = state
        self.epoch = epoch
        self.step = step
        self.cursor = cursor
        for k, v in extra.items():
            setattr(self, k, v)
        self._committed: dict | None = None
        self.commits = 0
        # Untracked convenience handle: `elastic.run` parks its client here
        # so train functions can reach the control plane (e.g. to build the
        # ElasticStateCallback) without threading it separately.
        self.client = None

    def commit(self) -> None:
        """Snapshot every tracked attribute to host memory. Call at clean
        boundaries only (between steps, outside collectives): at most one
        commit interval of progress is lost to a membership change.

        Cross-process-sharded leaves (ZeRO-1 optimizer shards, TP/FSDP
        weights) are snapshot as THIS process's owned pieces
        (`ShardedLeaf` — the `save_sharded` replica-0 dedup), keeping the
        commit communication-free; `gather_committed` reassembles them
        into dense host arrays at the membership-change boundary."""
        from horovod_tpu import trace

        with trace.span("commit", epoch=int(self.epoch),
                        step=int(self.step)):
            self._committed = {
                k: jax.tree_util.tree_map(_snap_leaf, getattr(self, k))
                for k in self._tracked
            }
            self.commits += 1

    @property
    def has_sharded_commit(self) -> bool:
        """Whether the committed snapshot still holds per-process pieces
        that must be reassembled (`gather_committed`) before the snapshot
        can travel or be restored as dense host state."""
        return self._committed is not None and _has_sharded(self._committed)

    def manifest(self) -> dict | None:
        """Summary of the committed snapshot — treedef, per-leaf global
        shapes/dtypes, this process's index specs and per-piece sha256
        digests, and the committed progress marker. The integrity record
        the reassembly path verifies against; also the debugging surface
        for 'what exactly did this member commit'."""
        if self._committed is None:
            return None
        leaves, treedef = jax.tree_util.tree_flatten(self._committed)
        entries = []
        for leaf in leaves:
            if isinstance(leaf, ShardedLeaf):
                entries.append({
                    "sharded": True, "shape": list(leaf.shape),
                    "dtype": leaf.dtype,
                    "pieces": sorted(leaf.pieces),
                    "sha256": dict(leaf.digests),
                })
            else:
                a = np.asarray(leaf)
                entries.append({
                    "sharded": False, "shape": list(a.shape),
                    "dtype": str(a.dtype),
                })
        return {
            "treedef": str(treedef),
            "progress": self.progress,
            "leaves": entries,
        }

    def gather_committed(self, force: bool = False) -> None:
        """Reassemble every committed `ShardedLeaf` into its dense global
        array, across the CURRENT membership — a collective (every process
        must call it at the same point).

        Each process contributes its owned pieces over one host-level
        object allgather (the KV transport), verifies every received piece
        against the committing process's sha256, and tiles the pieces into
        the global arrays with the sharded-checkpoint assembly logic
        (`checkpoint._assemble_global`). The elastic callback runs this at
        the membership-change boundary, while every member of the old
        generation — including a clean leaver — is still alive, so a
        3-process ZeRO-1 world shrinking to 2 keeps the departing third of
        the optimizer state.

        ``force=True`` makes a member with NO sharded commit (an
        empty-handed joiner, a dense-committed peer) still enter the
        allgather with an empty contribution — `sync` needs that so the
        collective stays lockstep when only SOME members' votes say
        sharded. Without force, no-sharded-commit is a communication-free
        no-op (the boundary path, where the classification is provably
        identical on every rank).

        Raises a RuntimeError naming the missing coverage when the pieces
        no longer tile an array (a member died hard before its pieces
        could travel): the caller's process then restarts and takes the
        checkpoint-restore fallback, which is the designed escalation."""
        from horovod_tpu import checkpoint

        sharded = self.has_sharded_commit
        if not sharded and not force:
            return
        payload: dict = {}
        digests: dict = {}
        leaves: list = []
        treedef = None
        if sharded:
            leaves, treedef = jax.tree_util.tree_flatten(self._committed)
            for i, leaf in enumerate(leaves):
                if isinstance(leaf, ShardedLeaf):
                    for spec, piece in leaf.pieces.items():
                        payload[f"{i}|{spec}"] = piece
                        digests[f"{i}|{spec}"] = leaf.digests[spec]
        store: dict = {}
        want: dict = {}
        for part_payload, part_digests in collectives.allgather_object(
            (payload, digests)
        ):
            store.update(part_payload)
            want.update(part_digests)
        if not sharded:
            return  # participated for lockstep; nothing to reassemble
        for key, piece in store.items():
            got = hashlib.sha256(
                np.ascontiguousarray(piece).tobytes()
            ).hexdigest()
            if got != want.get(key):
                raise RuntimeError(
                    f"elastic commit piece {key!r} failed its sha256 "
                    "check after transport — refusing to install "
                    "corrupt state; restart and restore from the last "
                    "checkpoint"
                )
        out = []
        for i, leaf in enumerate(leaves):
            if not isinstance(leaf, ShardedLeaf):
                out.append(leaf)
                continue
            try:
                out.append(checkpoint._assemble_global(
                    {k: v for k, v in store.items()
                     if k.startswith(f"{i}|")},
                    i, leaf.shape, np.dtype(leaf.dtype),
                ))
            except ValueError as e:
                raise RuntimeError(
                    f"cannot reassemble committed sharded state: {e}. "
                    "Pieces owned by a departed member never reached the "
                    "survivors (a hard death before the commit boundary); "
                    "restart and restore from the newest complete "
                    "checkpoint — the ElasticState fallback path."
                ) from None
        self._committed = jax.tree_util.tree_unflatten(treedef, out)

    def restore(self) -> tuple:
        """Roll tracked attributes back to the last commit (no-op before
        the first — a fresh member keeps its initial values and relies on
        `sync` or the checkpoint fallback). Returns the restored resume
        point ``(epoch, step)`` — what the next generation's train
        function hands to ``fit(initial_epoch=, initial_step=)`` so the
        run continues at the committed OPTIMIZER step, not the epoch
        boundary."""
        if self._committed is not None:
            for k, v in self._committed.items():
                setattr(self, k, v)
        return int(self.epoch), int(self.step)

    @property
    def progress(self) -> int:
        """Committed progress marker (-1 = nothing committed) — what the
        coordinator's root election compares across members."""
        if self._committed is None:
            return -1
        return progress_marker(
            self._committed.get("epoch", 0), self._committed.get("step", 0)
        )

    def _vote(self) -> tuple:
        """(structure fingerprint, progress, content digest, has-sharded)
        — what each member contributes to the sync agreement."""
        import pickle

        if self._committed is None:
            return (None, self.progress, None, False)
        leaves, treedef = jax.tree_util.tree_flatten(self._committed)
        fp = (
            str(treedef),
            tuple(getattr(l, "shape", ()) for l in leaves),
            tuple(str(getattr(l, "dtype", type(l).__name__))
                  for l in leaves),
        )
        digest = hashlib.sha256(pickle.dumps(self._committed)).hexdigest()
        return (fp, self.progress, digest, _has_sharded(self._committed))

    def sync(self, root_rank: int = 0) -> None:
        """Adopt the root member's committed snapshot, cross-process.

        A snapshot still holding per-process `ShardedLeaf` pieces (a
        commit that never passed a membership boundary's
        `gather_committed`) is first reassembled across the surviving
        membership — every member enters the gather when ANY member's
        vote says sharded, so the collective stays lockstep; pieces that
        no longer tile (a hard death took them) raise the actionable
        reassembly error, whose designed escalation is a per-rank restart
        into the checkpoint fallback.

        The common shrink then moves NOTHING: every survivor committed
        the same boundary of the same SPMD program, so when every
        member's (structure, progress, content-digest) vote matches the
        root's, everyone provably holds the root's bytes already and the
        model-sized transport is skipped (the digest — not just structure
        — guards against low-bit replica drift or rank-dependent tracked
        extras: any divergence falls through to the broadcast, exactly the
        pre-skip behavior). Otherwise, two transports, picked by what the
        members actually hold: identical structures ride
        `collectives.broadcast_pytree`, one fused host-level broadcast;
        differing structures or an empty-handed (re)joiner get the whole
        snapshot as one `broadcast_object` — structure included, so a
        fresh process needs no template. Ends with `restore()`, so live
        attributes reflect the adopted snapshot."""
        if jax.process_count() == 1:
            if self.has_sharded_commit:
                self.gather_committed()  # local-only; loud if incomplete
            self.restore()
            return
        votes = collectives.allgather_object(self._vote())
        if any(v[3] for v in votes):
            # Collective: every member enters, sharded commit or not
            # (force — a member without sharded pieces contributes an
            # empty payload rather than skipping the allgather).
            self.gather_committed(force=True)
            votes = collectives.allgather_object(self._vote())
        if all(v == votes[root_rank] for v in votes):
            self.restore()
            return
        fps = [v[0] for v in votes]
        # Uniform branch: fps comes from the allgather above, so every
        # rank evaluates the SAME condition and takes the SAME transport.
        if all(f is not None and f == fps[root_rank] for f in fps):  # hvt: noqa[HVT007]
            self._committed = collectives.broadcast_pytree(
                self._committed, root=root_rank
            )
        else:
            self._committed = collectives.broadcast_object(
                self._committed, root=root_rank
            )
        if self._committed is not None:
            self._committed = jax.device_get(self._committed)
        self.restore()


class ElasticStateCallback(Callback):
    """The trainer-side elastic hook: commit cadence + TCP heartbeats +
    the epoch-end membership agreement.

    Wire it into ``fit(callbacks=[...])`` from an `elastic.run` train
    function. Per epoch end it (1) tracks ``trainer.state`` into the
    `ElasticState`, (2) beats the coordinator, (3) allgathers every
    rank's view (coordinator generation + leave intent) so the WHOLE
    generation takes the same branch, and on a membership change
    (4) commits, runs the synchronized `runtime.shutdown` barrier, and
    raises `HostsUpdatedInterrupt` (survivors) or `LeaveInterrupt`
    (planned leavers — scheduler SIGTERM or the ``leave`` fault kind).

    ``commit_every``: commit every N epochs (1 = every epoch). A
    membership change always commits first regardless of cadence — the
    boundary is clean, so the just-finished epoch is never thrown away.

    ``commit_every_steps``: ADDITIONALLY commit every N optimizer steps
    within an epoch (0 = epoch-cadence only) — the sub-epoch cadence for
    long epochs. Commits land at ``on_batch_end``, which the STREAMED fit
    path fires once per optimizer step (per chunk with
    ``steps_per_execution>1`` — the cadence then commits at the next
    chunk boundary past N), so a hard crash there restores at most
    N (+chunk) steps behind instead of a whole epoch. With gradient
    accumulation (``backward_passes_per_step=K``) the K-microbatch scan
    lives inside the compiled step, so a commit can never land
    mid-accumulation with unreduced local grads: the alignment is
    structural, not scheduled. ``fit(cache='device')`` runs the epoch as
    one compiled scan by default (``on_batch_end`` once per epoch, so
    commits stay epoch-granular there) — set ``HVT_EPOCH_CHUNK_STEPS=C``
    to split the epoch into compiled C-step chunks, which fires
    ``on_batch_end`` per chunk and makes this cadence (and
    ``rescale_every_steps``) work on the device-cached path too.
    Mid-epoch commits record ``(epoch, step)`` progress
    (`progress_marker` orders them under the epoch-end commit), which
    drives root election after a crash — and the training loop resumes
    AT that step: `ElasticState.restore` hands back ``(epoch, step)``
    and the train function passes both to ``fit(initial_epoch=,
    initial_step=)``, whose feeding paths deterministically fast-forward
    the data to the committed optimizer step (zero replayed steps).

    ``rescale_every_steps``: ADDITIONALLY run the membership agreement
    every N optimizer steps within an epoch (0 = epoch boundaries only)
    — the sub-epoch rescale cadence for long epochs. Steady-state rounds
    cost one cheap boolean agreement (`agree_any`): the coordinator
    piggybacks a ``pending`` membership flag on heartbeat replies, so a
    rank only escalates to the full vote when some rank saw a pending
    generation bump or leave intent. On agreement the boundary runs
    exactly like the epoch-end one — commit at the CURRENT ``(epoch,
    step)``, sharded reassembly if anyone is departing, lockstep
    `runtime.shutdown` at the step boundary, interrupt — so a joiner is
    admitted (and a clean leaver released) within N optimizer steps
    instead of waiting out the epoch. Like ``commit_every_steps``, the
    cadence is accumulation-aligned by construction, and on
    ``fit(cache='device')`` it is epoch-granular unless the epoch is
    step-chunked (``HVT_EPOCH_CHUNK_STEPS``).

    Defaults read the job-spec surface: ``HVT_COMMIT_EVERY`` /
    ``HVT_COMMIT_EVERY_STEPS`` / ``HVT_RESCALE_EVERY_STEPS`` (set by the
    supervisor from the ``elastic:`` block's ``commit_every`` /
    ``commit_every_steps`` / ``rescale_every_steps`` keys).

    SIGTERM: a handler installed for the duration of fit() records the
    signal as leave intent, so a scheduler preemption becomes a clean
    shrink at the next epoch boundary instead of a fleet abort. Don't
    stack this with `PreemptionCheckpointCallback` — both would claim
    the same signal."""

    def __init__(self, state: ElasticState, client, *,
                 commit_every: int | None = None,
                 commit_every_steps: int | None = None,
                 rescale_every_steps: int | None = None,
                 beat_interval: float = 1.0):
        from horovod_tpu.analysis import registry

        self.state = state
        self.client = client
        if commit_every is None:
            commit_every = registry.get_int("HVT_COMMIT_EVERY")
        self.commit_every = max(1, int(commit_every))
        if commit_every_steps is None:
            commit_every_steps = registry.get_int("HVT_COMMIT_EVERY_STEPS")
        self.commit_every_steps = max(0, int(commit_every_steps))
        if rescale_every_steps is None:
            rescale_every_steps = registry.get_int("HVT_RESCALE_EVERY_STEPS")
        self.rescale_every_steps = max(0, int(rescale_every_steps))
        self.beat_interval = beat_interval
        self._last_beat = 0.0
        self._leave_requested = False
        self._old_handler = None
        self._epoch = 0
        self._last_commit_step = 0
        self._last_rescale_step = 0

    # --- liveness ----------------------------------------------------------

    def _beat(self, force: bool = False) -> int | None:
        import time

        now = time.time()
        if not force and now - self._last_beat < self.beat_interval:
            return None
        try:
            gen = self.client.beat(progress=self.state.progress)
        except CONTROL_PLANE_ERRORS:
            # A dead coordinator must not kill training mid-epoch; the
            # next sync/leave will surface the failure loudly.
            return None
        self._last_beat = now
        return gen

    def _handler(self, signum, frame):
        self._leave_requested = True
        signal_leave()

    def on_train_begin(self, logs=None):
        # Fail fast — at elastic.run entry of every generation, before a
        # single step trains — for cross-process-sharded layouts the
        # per-shard commit cannot reassemble (see validate_committable).
        if self.trainer is not None and getattr(
            self.trainer, "state", None
        ) is not None:
            validate_committable(
                self.trainer.state, where="elastic.run (tracked state)"
            )
        self._old_handler = signal.signal(signal.SIGTERM, self._handler)
        self._beat(force=True)

    def on_train_end(self, logs=None):
        if self._old_handler is not None:
            signal.signal(signal.SIGTERM, self._old_handler)
            self._old_handler = None

    def on_epoch_begin(self, epoch: int, logs=None):
        self._epoch = epoch
        # Step cadences measure from the TRUE resume point: a fit resumed
        # mid-epoch (initial_step=S) fires its first on_batch_end at step
        # S+1, and a zero baseline would make every cadence fire
        # immediately on resume.
        base = 0
        if self.trainer is not None and epoch == getattr(
            self.trainer, "_resume_epoch", 0
        ):
            base = int(getattr(self.trainer, "_resume_step", 0))
        self._last_commit_step = base
        self._last_rescale_step = base
        self._beat(force=True)

    def on_batch_end(self, batch: int, logs=None):
        self._beat()
        # ``batch`` indexes OPTIMIZER steps (the Trainer fires this hook
        # once per compiled execution — per optimizer step at
        # steps_per_execution=1, per chunk otherwise), so a commit here is
        # always at an accumulation boundary: K-microbatch accumulation
        # runs INSIDE the step and never leaves unreduced local grads
        # across the hook. >= (not ==) so steps_per_execution chunks that
        # stride past the cadence still commit at the next boundary.
        done = batch + 1
        if (
            self.commit_every_steps
            and done - self._last_commit_step >= self.commit_every_steps
        ):
            self._last_commit_step = done
            self.state.state = self.trainer.state
            self.state.epoch = self._epoch
            self.state.step = done
            self.state.cursor = self._stream_cursor(self._epoch, done)
            self.state.commit()
        self._maybe_step_rescale(done)

    def _stream_cursor(self, epoch: int, step: int):
        """The trainer's durable data-stream cursor for the committed
        position (None for trainers/fakes without one) — committed and
        synced with the snapshot, so the next generation resumes the
        SAME anchored byte stream (`data.stream`)."""
        fn = getattr(self.trainer, "stream_cursor", None)
        return fn(epoch, step) if callable(fn) else None

    def _maybe_step_rescale(self, done: int) -> None:
        """The SUB-EPOCH membership agreement (``rescale_every_steps``):
        at the cadence's step boundaries, agree fleet-wide whether the
        membership changed and, if so, run the same commit → (sharded
        reassembly) → lockstep-teardown boundary the epoch end runs —
        at the CURRENT optimizer step, so survivors resume with
        ``initial_step`` and zero replayed steps, and joiners/leavers
        wait at most N steps instead of an epoch."""
        from horovod_tpu.testing import faults

        if not self.rescale_every_steps:
            return
        if done - self._last_rescale_step < self.rescale_every_steps:
            return
        self._last_rescale_step = done
        gen = self._beat(force=True)
        leaving = (self._leave_requested or leave_signaled()
                   or faults.leave_requested())
        pending = bool(
            leaving
            or getattr(self.client, "last_beat_pending", False)
            or (gen is not None and gen != self.client.synced_generation)
        )
        # Steady state costs ONE boolean agreement: the coordinator
        # piggybacks the pending-membership flag on the heartbeat reply,
        # so unless some rank saw a generation drift or leave intent the
        # round ends here.
        if not agree_any(pending):
            return
        if jax.process_count() > 1:
            votes = collectives.allgather_object(
                (gen if gen is not None else -1, bool(leaving))
            )
            agreed_gen = max(g for g, _ in votes)
            any_leaving = any(l for _, l in votes)
        else:
            agreed_gen = gen if gen is not None else -1
            any_leaving = bool(leaving)
        changed = (
            any_leaving
            or (agreed_gen >= 0
                and agreed_gen != self.client.synced_generation)
        )
        if not changed:
            return  # the pending flag raced a settle; next cadence re-checks
        # Clean STEP boundary: bank progress at (epoch, done) — the
        # resumed generation fast-forwards its data to exactly here —
        # then tear down in lockstep (the votes above guarantee every
        # rank of the generation reaches this barrier at the same step).
        self.state.state = self.trainer.state
        self.state.epoch = self._epoch
        self.state.step = done
        self.state.cursor = self._stream_cursor(self._epoch, done)
        self.state.commit()
        if self.state.has_sharded_commit and any_leaving:
            # Same departure-only reassembly rule as the epoch boundary
            # (grow-only changes defer to sync's reassembly on the new
            # world) — see on_epoch_end for the full rationale.
            self.state.gather_committed()
        self._teardown_and_interrupt(leaving)

    def _teardown_and_interrupt(self, leaving: bool):
        """The shared tail of both membership boundaries: synchronized
        runtime teardown, then the interrupt that unwinds fit()."""
        from horovod_tpu.testing import faults

        runtime.shutdown()
        if leaving:
            try:
                self.client.leave(
                    reason="fault" if faults.leave_requested() else "sigterm"
                )
            except CONTROL_PLANE_ERRORS:
                pass
            clear_leave_signal()
            raise LeaveInterrupt()
        raise HostsUpdatedInterrupt()

    # --- the commit + agreement boundary -----------------------------------

    def on_epoch_end(self, epoch: int, logs=None):
        from horovod_tpu.testing import faults

        self.state.state = self.trainer.state
        self.state.epoch = epoch + 1
        self.state.step = 0
        self.state.cursor = self._stream_cursor(epoch + 1, 0)
        gen = self._beat(force=True)
        leaving = (self._leave_requested or leave_signaled()
                   or faults.leave_requested())
        if jax.process_count() > 1:
            votes = collectives.allgather_object(
                (gen if gen is not None else -1, bool(leaving))
            )
            agreed_gen = max(g for g, _ in votes)
            any_leaving = any(l for _, l in votes)
        else:
            agreed_gen = gen if gen is not None else -1
            any_leaving = bool(leaving)
        changed = (
            any_leaving
            or (agreed_gen >= 0
                and agreed_gen != self.client.synced_generation)
        )
        if not changed:
            if (epoch + 1) % self.commit_every == 0:
                self.state.commit()
            return
        # Clean boundary: bank the finished epoch, then tear the old world
        # down in lockstep (every rank of the generation reaches this
        # barrier — the votes above guarantee the same branch everywhere).
        self.state.commit()
        if self.state.has_sharded_commit and any_leaving:
            # Reassemble per-process pieces (ZeRO-1/TP/FSDP commits) while
            # every member of the OLD generation — including a clean
            # leaver — is still here: after the teardown below, a departed
            # member's share of the state is gone for good. Collective;
            # the sharded/dense classification is a function of the shared
            # SPMD state, so every rank takes this branch together, and
            # any_leaving comes from the same allgather'd votes.
            #
            # Grow-only fast path: when NO member is departing (the
            # generation bump is a joiner waiting in rendezvous — a hard
            # death never reaches this agreement, it kills the collective
            # above first), every piece's owner survives into the next
            # generation, so the model-sized piece-allgather is deferred:
            # survivors keep their compact sharded commits through the
            # teardown, and `sync` on the new world sees the sharded
            # votes and runs the lockstep reassembly there, which also
            # covers the empty-handed joiners. Trade-off, accepted
            # deliberately (ROADMAP PR 3 follow-up): a survivor dying
            # HARD inside the teardown→sync window now takes its pieces
            # with it — sync's reassembly then raises the actionable
            # coverage error and the fleet falls back to the newest
            # checkpoint, exactly the designed hard-death escalation
            # (the same death DURING the old boundary gather lost the
            # same progress; only the window is slightly wider).
            self.state.gather_committed()
        self._teardown_and_interrupt(leaving)
