"""Elastic training state — the Horovod ``State.commit()/restore()``
pattern, JAX-native.

The contract that makes in-process rescaling possible: everything a worker
needs to continue training after the world changes must exist as a HOST
(numpy) snapshot, because the rescale drops every live ``jax.Array`` along
with the old backends (`compat.clear_backends`). `ElasticState.commit`
takes that snapshot at clean boundaries (epoch ends, or every N steps);
`restore` rolls the live attributes back to it after a membership-change
interrupt; `sync` moves the freshest committed snapshot to (re)joining
members over ONE fused host-level broadcast — no checkpoint round-trip for
the common case (the checkpoint path stays as the fallback for members
whose process itself was restarted).

`ElasticStateCallback` is the commit hook wired into the `Trainer` loop:
it tracks the trainer's state into the `ElasticState`, commits on the
chosen cadence, carries TCP heartbeats to the coordinator, and runs the
epoch-end **membership agreement** — the same allgather-agreement shape
`PreemptionCheckpointCallback` uses for signals — so every rank of a
generation tears down and re-rendezvouses at the SAME epoch boundary.
That lockstep is what lets `runtime.shutdown` complete its barrier
cleanly (a one-sided teardown makes the coordination service kill the
survivors; see `compat.distributed_shutdown_barrier`).
"""

from __future__ import annotations

import signal

import jax

from horovod_tpu import runtime
from horovod_tpu.elastic.coordinator import ElasticError
from horovod_tpu.parallel import collectives
from horovod_tpu.training.callbacks import Callback

# What a control-plane call can throw when the coordinator is dying or
# racing teardown: socket errors, a mid-exchange close / error reply
# (ElasticError), or a torn JSON line (json.JSONDecodeError ⊂ ValueError).
CONTROL_PLANE_ERRORS = (OSError, ElasticError, ValueError)


class HostsUpdatedInterrupt(BaseException):
    """The world changed (a member joined/left/died): unwind out of fit(),
    restore committed state, re-rendezvous. BaseException so user-level
    ``except Exception`` blocks in training code cannot swallow it."""


class LeaveInterrupt(BaseException):
    """This member is leaving the fleet (planned departure: a scheduler
    SIGTERM, or the ``leave`` fault kind). `elastic.run` converts it into
    the 143 exit-status convention the supervisor classifies as clean."""


def progress_marker(epoch: int, step: int = 0) -> int:
    """Total order over committed progress: epochs dominate, steps break
    ties within an epoch (the every-N-steps commit cadence). Used to elect
    the rendezvous root — the member whose snapshot everyone adopts."""
    return int(epoch) * 1_000_000 + int(step)


class ElasticState:
    """Committed training state: named attributes (``state`` — typically a
    `TrainState` — plus ``epoch``/``step`` bookkeeping and any extra
    kwargs), snapshotted to host memory on ``commit()``.

    Attributes named at construction are the tracked set; assign to them
    freely between commits. After ``restore()`` array-valued attributes
    hold HOST (numpy) pytrees — `Trainer.install_state` puts them back on
    whatever mesh the new world built."""

    def __init__(self, state=None, epoch: int = 0, step: int = 0, **extra):
        self._tracked = ("state", "epoch", "step", *extra)
        self.state = state
        self.epoch = epoch
        self.step = step
        for k, v in extra.items():
            setattr(self, k, v)
        self._committed: dict | None = None
        self.commits = 0
        # Untracked convenience handle: `elastic.run` parks its client here
        # so train functions can reach the control plane (e.g. to build the
        # ElasticStateCallback) without threading it separately.
        self.client = None

    def commit(self) -> None:
        """Snapshot every tracked attribute to host memory. Call at clean
        boundaries only (between steps, outside collectives): at most one
        commit interval of progress is lost to a membership change."""
        self._committed = {
            k: jax.device_get(getattr(self, k)) for k in self._tracked
        }
        self.commits += 1

    def restore(self) -> None:
        """Roll tracked attributes back to the last commit (no-op before
        the first — a fresh member keeps its initial values and relies on
        `sync` or the checkpoint fallback)."""
        if self._committed is None:
            return
        for k, v in self._committed.items():
            setattr(self, k, v)

    @property
    def progress(self) -> int:
        """Committed progress marker (-1 = nothing committed) — what the
        coordinator's root election compares across members."""
        if self._committed is None:
            return -1
        return progress_marker(
            self._committed.get("epoch", 0), self._committed.get("step", 0)
        )

    def sync(self, root_rank: int = 0) -> None:
        """Adopt the root member's committed snapshot, cross-process.

        The common shrink moves NOTHING: every survivor committed the same
        boundary of the same SPMD program, so when every member's
        (structure, progress, content-digest) vote matches the root's,
        everyone provably holds the root's bytes already and the
        model-sized transport is skipped (the digest — not just structure
        — guards against low-bit replica drift or rank-dependent tracked
        extras: any divergence falls through to the broadcast, exactly the
        pre-skip behavior). Otherwise, two transports, picked by what the
        members actually hold: identical structures ride
        `collectives.broadcast_pytree`, one fused host-level broadcast;
        differing structures or an empty-handed (re)joiner get the whole
        snapshot as one `broadcast_object` — structure included, so a
        fresh process needs no template. Ends with `restore()`, so live
        attributes reflect the adopted snapshot."""
        import hashlib
        import pickle

        if jax.process_count() == 1:
            self.restore()
            return
        fp = None
        digest = None
        if self._committed is not None:
            leaves, treedef = jax.tree_util.tree_flatten(self._committed)
            fp = (
                str(treedef),
                tuple(getattr(l, "shape", ()) for l in leaves),
                tuple(str(getattr(l, "dtype", type(l).__name__))
                      for l in leaves),
            )
            digest = hashlib.sha256(
                pickle.dumps(self._committed)
            ).hexdigest()
        votes = collectives.allgather_object((fp, self.progress, digest))
        if all(v == votes[root_rank] for v in votes):
            self.restore()
            return
        fps = [f for f, _, _ in votes]
        if all(f is not None and f == fps[root_rank] for f in fps):
            self._committed = collectives.broadcast_pytree(
                self._committed, root=root_rank
            )
        else:
            self._committed = collectives.broadcast_object(
                self._committed, root=root_rank
            )
        if self._committed is not None:
            self._committed = jax.device_get(self._committed)
        self.restore()


class ElasticStateCallback(Callback):
    """The trainer-side elastic hook: commit cadence + TCP heartbeats +
    the epoch-end membership agreement.

    Wire it into ``fit(callbacks=[...])`` from an `elastic.run` train
    function. Per epoch end it (1) tracks ``trainer.state`` into the
    `ElasticState`, (2) beats the coordinator, (3) allgathers every
    rank's view (coordinator generation + leave intent) so the WHOLE
    generation takes the same branch, and on a membership change
    (4) commits, runs the synchronized `runtime.shutdown` barrier, and
    raises `HostsUpdatedInterrupt` (survivors) or `LeaveInterrupt`
    (planned leavers — scheduler SIGTERM or the ``leave`` fault kind).

    ``commit_every``: commit every N epochs (1 = every epoch). A
    membership change always commits first regardless of cadence — the
    boundary is clean, so the just-finished epoch is never thrown away.

    SIGTERM: a handler installed for the duration of fit() records the
    signal as leave intent, so a scheduler preemption becomes a clean
    shrink at the next epoch boundary instead of a fleet abort. Don't
    stack this with `PreemptionCheckpointCallback` — both would claim
    the same signal."""

    def __init__(self, state: ElasticState, client, *,
                 commit_every: int = 1, beat_interval: float = 1.0):
        self.state = state
        self.client = client
        self.commit_every = max(1, int(commit_every))
        self.beat_interval = beat_interval
        self._last_beat = 0.0
        self._leave_requested = False
        self._old_handler = None

    # --- liveness ----------------------------------------------------------

    def _beat(self, force: bool = False) -> int | None:
        import time

        now = time.time()
        if not force and now - self._last_beat < self.beat_interval:
            return None
        try:
            gen = self.client.beat(progress=self.state.progress)
        except CONTROL_PLANE_ERRORS:
            # A dead coordinator must not kill training mid-epoch; the
            # next sync/leave will surface the failure loudly.
            return None
        self._last_beat = now
        return gen

    def _handler(self, signum, frame):
        self._leave_requested = True

    def on_train_begin(self, logs=None):
        self._old_handler = signal.signal(signal.SIGTERM, self._handler)
        self._beat(force=True)

    def on_train_end(self, logs=None):
        if self._old_handler is not None:
            signal.signal(signal.SIGTERM, self._old_handler)
            self._old_handler = None

    def on_epoch_begin(self, epoch: int, logs=None):
        self._beat(force=True)

    def on_batch_end(self, batch: int, logs=None):
        self._beat()

    # --- the commit + agreement boundary -----------------------------------

    def on_epoch_end(self, epoch: int, logs=None):
        from horovod_tpu.testing import faults

        self.state.state = self.trainer.state
        self.state.epoch = epoch + 1
        self.state.step = 0
        gen = self._beat(force=True)
        leaving = self._leave_requested or faults.leave_requested()
        if jax.process_count() > 1:
            votes = collectives.allgather_object(
                (gen if gen is not None else -1, bool(leaving))
            )
            agreed_gen = max(g for g, _ in votes)
            any_leaving = any(l for _, l in votes)
        else:
            agreed_gen = gen if gen is not None else -1
            any_leaving = bool(leaving)
        changed = (
            any_leaving
            or (agreed_gen >= 0
                and agreed_gen != self.client.synced_generation)
        )
        if not changed:
            if (epoch + 1) % self.commit_every == 0:
                self.state.commit()
            return
        # Clean boundary: bank the finished epoch, then tear the old world
        # down in lockstep (every rank of the generation reaches this
        # barrier — the votes above guarantee the same branch everywhere).
        self.state.commit()
        runtime.shutdown()
        if leaving:
            try:
                self.client.leave(
                    reason="fault" if faults.leave_requested() else "sigterm"
                )
            except CONTROL_PLANE_ERRORS:
                pass
            raise LeaveInterrupt()
        raise HostsUpdatedInterrupt()
