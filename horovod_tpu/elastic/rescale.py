"""Rescale machinery: rebuild the runtime for a settled world, and the
worker-side elastic driver loop.

The loop is Horovod Elastic's ``@hvd.elastic.run`` shape, adapted to the
jitted-SPMD world: since the mesh, every compiled executable, and every
live array are functions of the world size, a rescale rebuilds ALL of
them — `ensure_world` tears down jax's distributed runtime and backends
and re-initializes at the rendezvous size, and the user's ``train_fn``
reconstructs its Trainer (fresh jit caches compile for the new topology).
What survives a rescale is exactly the `ElasticState`'s committed host
snapshot — params, optimizer state, epoch — moved to (re)joiners by
``state.sync`` over the freshly built world.
"""

from __future__ import annotations

import os
import signal
import threading

from horovod_tpu import runtime
from horovod_tpu.elastic import state as state_lib
from horovod_tpu.elastic.coordinator import ElasticClient, WorldInfo
from horovod_tpu.elastic.state import (
    ElasticState,
    HostsUpdatedInterrupt,
    LeaveInterrupt,
)


def ensure_world(world: WorldInfo) -> "runtime.World":
    """(Re)build the process's runtime for a settled rendezvous world.

    First call in a fresh process: plain `runtime.init`. Later calls (a
    rescale): the old world was already shut down at the agreement
    boundary (`ElasticStateCallback` runs the synchronized barrier), so
    what remains is dropping the stale backends and initializing at the
    new size. A world of size 1 skips `jax.distributed` entirely — the
    bare single-process mode, every collective a local op — so a fleet
    can shrink all the way to one survivor."""
    if world.size > 1:
        return runtime.reinit(
            coordinator_address=world.jax_coordinator,
            num_processes=world.size,
            process_id=world.rank,
        )
    return runtime.reinit()


def run(
    train_fn,
    state: ElasticState | None = None,
    *,
    client: ElasticClient | None = None,
    address: str | None = None,
    member_id: str | None = None,
    max_generations: int = 1000,
):
    """Drive ``train_fn`` through rendezvous generations until it returns.

    ``train_fn(state, world)`` must build its Trainer FOR the given world
    (meshes, `scale_lr`, steps-per-epoch all react to ``world.size``),
    adopt ``state`` (``trainer.install_state(state.state)`` when a
    committed snapshot exists, the checkpoint-restore idiom otherwise),
    include ``ElasticStateCallback(state, client)`` in its fit callbacks
    (LAST in the list, so earlier callbacks see each epoch before a
    rescale can interrupt it), and train from ``state.epoch`` AND
    ``state.step`` — pass both to ``fit(initial_epoch=state.epoch,
    initial_step=state.step)`` so a generation that rescaled mid-epoch
    resumes at the committed OPTIMIZER step with the data iterator
    deterministically fast-forwarded (zero replayed steps), not at the
    epoch boundary.

    Per generation: rendezvous (`client.sync` — blocks until the world
    settles), rebuild the runtime (`ensure_world`), adopt the freshest
    committed snapshot (`state.sync` from the coordinator-elected root —
    ordered by `progress_marker(epoch, step)`, so a mid-epoch commit
    outranks the same epoch's start), then hand over to ``train_fn``. A
    `HostsUpdatedInterrupt` rolls state back to the last commit —
    `state.restore()` hands back the ``(epoch, step)`` resume point —
    and loops; a `LeaveInterrupt` notifies the
    coordinator (already done at the boundary) and exits with status 143
    — the preemption convention the supervisor classifies as a planned,
    clean departure. Normal return reports ``done`` and hands back
    ``train_fn``'s result.

    Cross-process-sharded tracked state (ZeRO-1/TP/FSDP) is supported
    end to end: commits snapshot per-process pieces, the membership
    boundary reassembles them across the departing generation, and
    `state.sync` hands every survivor the dense snapshot to re-place on
    the new world's mesh (`Trainer.install_state`). Layouts the
    per-shard commit cannot reassemble fail fast at entry — the elastic
    callback validates the tracked state at train begin
    (`state.validate_committable`) before any step runs."""
    client = client or ElasticClient(address, member_id)
    state = state or ElasticState()
    state.client = client
    from horovod_tpu import trace

    for _ in range(max_generations):
        if state_lib.leave_signaled():
            # A scheduler SIGTERM landed between generations (or during
            # the previous teardown): leave NOW instead of joining a
            # rendezvous we'd only depart again at the first boundary.
            try:
                client.leave(reason="sigterm")
            except state_lib.CONTROL_PLANE_ERRORS:
                pass
            state_lib.clear_leave_signal()
            raise SystemExit(143)
        # One span per rescale boundary: rendezvous wait + runtime
        # rebuild + state sync — the wall-clock a membership change
        # costs this worker before training resumes.
        with trace.span("rescale"):
            world = client.sync(progress=state.progress)
            ensure_world(world)
            # `jax.distributed.initialize` (inside ensure_world) installs
            # XLA's preemption notifier over SIGTERM; claim the signal
            # back IMMEDIATELY so a preemption arriving before fit()'s
            # own handler (trainer build, data setup, first compile) is
            # recorded as sticky leave intent instead of being eaten —
            # see `state.signal_leave`.
            if threading.current_thread() is threading.main_thread():
                signal.signal(signal.SIGTERM, state_lib.signal_leave)
            state.sync(world.root_rank)
        try:
            result = train_fn(state, world)
        except HostsUpdatedInterrupt:
            state.restore()
            continue
        except LeaveInterrupt:
            raise SystemExit(143)
        try:
            client.leave(reason="done")
        except state_lib.CONTROL_PLANE_ERRORS:
            pass  # supervisor may already be tearing the fleet down
        return result
    raise RuntimeError(
        f"elastic run exceeded {max_generations} generations — the fleet "
        "is thrashing (check the supervisor journal for a rescale loop)"
    )


def member_id_from_env() -> str | None:
    """The supervisor-assigned member identity, if launched elastically."""
    from horovod_tpu.analysis import registry

    return registry.get_str(runtime.ENV_ELASTIC_MEMBER)
