"""TensorBoard event-file writer — the real tfevents format, no TF dependency.

Parity target: the reference writes genuine TensorBoard event files at batch
frequency (`tf.keras.callbacks.TensorBoard(log_dir=..., update_freq='batch')`,
tensorflow2_keras_mnist.py:89; mnist_keras.py:105). `ScalarLogger` keeps its
JSONL stream for the CI gate, and ALSO writes this format so
``tensorboard --logdir`` can plot a run.

The format, implemented from scratch (~100 lines total):

* **TFRecord framing** — each record is
  ``uint64 length · uint32 masked_crc(length) · bytes · uint32 masked_crc(bytes)``
  where the checksum is CRC-32C (Castagnoli) with TensorFlow's rotation mask
  ``((crc >> 15 | crc << 17) + 0xa282ead8)``.
* **Event protobuf** — hand-encoded wire format (varint tags; no generated
  code): ``Event{wall_time=1:double, step=2:int64, file_version=3:string,
  summary=5:Summary}``; ``Summary{value=1:repeated Value}``;
  ``Value{tag=1:string, simple_value=2:float}``.
* First record of every file is the ``brain.Event:2`` version sentinel, as
  TensorBoard's loader expects; filenames follow the
  ``events.out.tfevents.<unix-time>.<hostname>`` convention.
"""

from __future__ import annotations

import os
import socket
import struct
import time

# --- CRC-32C (Castagnoli), table-driven ------------------------------------

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 * (_c & 1))
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --- minimal protobuf wire encoding ----------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        bits = n & 0x7F
        n >>= 7
        if n:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3 | 0) + _varint(value)


def _field_fixed64(num: int, value: float) -> bytes:
    return _varint(num << 3 | 1) + struct.pack("<d", value)


def _field_fixed32(num: int, value: float) -> bytes:
    return _varint(num << 3 | 5) + struct.pack("<f", value)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint(num << 3 | 2) + _varint(len(payload)) + payload


def encode_event(
    wall_time: float,
    step: int | None = None,
    file_version: str | None = None,
    scalars: dict[str, float] | None = None,
) -> bytes:
    """Serialize one tensorboard ``Event`` message."""
    msg = _field_fixed64(1, wall_time)
    if step is not None:
        msg += _field_varint(2, int(step) & 0xFFFFFFFFFFFFFFFF)
    if file_version is not None:
        msg += _field_bytes(3, file_version.encode())
    if scalars:
        summary = b"".join(
            _field_bytes(
                1,
                _field_bytes(1, tag.encode()) + _field_fixed32(2, float(v)),
            )
            for tag, v in scalars.items()
        )
        msg += _field_bytes(5, summary)
    return msg


def encode_record(payload: bytes) -> bytes:
    """Wrap a serialized message in TFRecord framing."""
    header = struct.pack("<Q", len(payload))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + payload
        + struct.pack("<I", _masked_crc(payload))
    )


_writer_seq = 0


class TBEventWriter:
    """Scalar event writer for one run directory; each writer owns a fresh
    uniquely-named file (time + hostname + pid + sequence — two writers in
    the same second must not interleave streams in one file)."""

    def __init__(self, log_dir: str):
        global _writer_seq
        os.makedirs(log_dir, exist_ok=True)
        _writer_seq += 1
        name = (
            f"events.out.tfevents.{int(time.time())}."
            f"{socket.gethostname()}.{os.getpid()}.{_writer_seq}"
        )
        self.path = os.path.join(log_dir, name)
        self._fh = open(self.path, "wb")
        self._write(
            encode_event(time.time(), file_version="brain.Event:2")
        )

    def _write(self, payload: bytes) -> None:
        self._fh.write(encode_record(payload))

    def scalars(
        self, values: dict[str, float], step: int, wall_time: float | None = None
    ) -> None:
        self._write(
            encode_event(
                wall_time if wall_time is not None else time.time(),
                step=step,
                scalars=values,
            )
        )

    def scalar(self, tag: str, value: float, step: int, wall_time=None) -> None:
        self.scalars({tag: value}, step, wall_time)

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


def read_records(path: str):
    """Parse a tfevents file back into raw message payloads, verifying both
    CRCs — the test-side inverse of the writer (and a debugging aid)."""
    out = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                return out
            (hcrc,) = struct.unpack("<I", f.read(4))
            if hcrc != _masked_crc(header):
                raise ValueError("corrupt length crc")
            (length,) = struct.unpack("<Q", header)
            payload = f.read(length)
            (pcrc,) = struct.unpack("<I", f.read(4))
            if pcrc != _masked_crc(payload):
                raise ValueError("corrupt payload crc")
            out.append(payload)
