"""Process/device bootstrap and topology queries.

TPU-native replacement for ``hvd.init()`` and the rank/size surface
(reference: tensorflow2_keras_mnist.py:25,28-32, mnist_keras.py:30,33-36;
SURVEY.md §3.3).

Design notes (vs the Horovod model):

* Horovod: one process per GPU; ``hvd.init()`` runs MPI_Init, starts a C++
  coordinator thread, and the script pins one GPU by ``local_rank()``.
* Here: one process per *host*, each driving all its local TPU chips;
  ``init()`` wires up `jax.distributed` over DCN when a coordinator is
  configured and is a no-op for single-process runs — the reference's
  "no-launcher degradation" requirement (README.md:49-52) holds: the same
  script runs unlaunched with ``size() == 1`` on one chip/CPU.
* Device pinning is obsolete: `jax.local_devices()` enumerates the chips and
  SPMD sharding places data; there is nothing to pin.

Topology mapping (the unit of data parallelism is the *chip*, not the
process):

===================  =========================================================
Horovod concept      horovod_tpu equivalent
===================  =========================================================
``hvd.size()``       ``size()`` → ``jax.device_count()`` (total chips). This
                     is the number LR scaling and work division react to
                     (tensorflow2_keras_mnist.py:55,96).
``hvd.rank()``       ``rank()`` → ``jax.process_index()``. Used for
                     single-writer gating (checkpoints/TB on rank 0,
                     tensorflow2_keras_mnist.py:86-92).
``hvd.local_rank()`` ``local_rank()`` → this process's ordinal among
                     processes on the same host (0 in the standard
                     one-process-per-host deployment).
``hvd.local_size()`` ``local_size()`` → number of chips attached to this
                     process (``jax.local_device_count()``).
===================  =========================================================
"""

from __future__ import annotations

import dataclasses
import os
import socket

import jax

from horovod_tpu.analysis import registry

# Environment variables understood by init(), mirroring the role of
# mpirun's `-x` env propagation + /generated/hostfile (README.md:57).
ENV_COORDINATOR = "HVT_COORDINATOR_ADDRESS"
ENV_NUM_PROCESSES = "HVT_NUM_PROCESSES"
ENV_PROCESS_ID = "HVT_PROCESS_ID"
ENV_LOCAL_RANK = "HVT_LOCAL_RANK"
# Platform override for launched children (testing the multi-process path on
# CPU). JAX_PLATFORMS alone is not reliable when a site hook force-registers
# an accelerator platform at interpreter start; init() applies these to
# jax.config directly, which must happen before any backend use.
ENV_PLATFORM = "HVT_PLATFORM"
ENV_NUM_CPU_DEVICES = "HVT_NUM_CPU_DEVICES"
# Liveness contract with the restart supervisor (launch/supervisor.py):
# when set, fit() auto-installs callbacks.HeartbeatCallback, which touches
# $HVT_HEARTBEAT_DIR/rank-<process rank> through training; the supervisor
# kills and relaunches a fleet whose newest beat goes stale. Examples need
# no changes — the supervisor exports the variable, fit() reacts.
ENV_HEARTBEAT_DIR = "HVT_HEARTBEAT_DIR"
# Elastic rendezvous (horovod_tpu.elastic): the supervisor's coordinator
# address ("host:port") and this process's stable member identity. Set by
# `hvt-launch run/pod --elastic`; consumed by `elastic.run`, NOT by init()
# — in elastic mode the world (size/rank/jax coordinator) comes from a
# rendezvous round, not from static env assignment.
ENV_ELASTIC_COORDINATOR = "HVT_ELASTIC_COORDINATOR"
ENV_ELASTIC_MEMBER = "HVT_ELASTIC_MEMBER"

_initialized = False


def env_flag(name: str) -> bool:
    """Shared boolean env-var contract: unset/''/'0'/'false'/'no' are off
    (case-insensitive), anything else is on. Used for every HVT_* switch so
    the accepted spellings can't drift between call sites — the contract
    itself lives in `analysis.registry.flag_like` (the knob registry)."""
    return registry.flag_like(os.environ.get(name))


@dataclasses.dataclass(frozen=True)
class World:
    """Snapshot of the distributed topology after init()."""

    process_rank: int
    process_count: int
    local_rank: int
    device_count: int
    local_device_count: int
    hostname: str
    platform: str

    @property
    def is_distributed(self) -> bool:
        return self.process_count > 1


def init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> World:
    """Initialize the distributed runtime. Idempotent, like ``hvd.init()``.

    Resolution order for each argument: explicit argument → HVT_* env var →
    unset. If no coordinator is configured the run is single-process
    (``process_count() == 1``) and every collective degrades to a local op —
    the reference's bare ``python script.py`` mode (README.md:49-52).

    Under a launcher (`horovod_tpu.launch`), the HVT_* env vars play the role
    of mpirun's slot mapping: the launcher assigns process ids and propagates
    the coordinator address, replacing `/generated/hostfile`
    (distributed-keras-sample.yaml:8).
    """
    global _initialized
    if _initialized:
        return world()

    # A process calling init() is a WORKER: start its collective flight
    # recorder if HVT_FLIGHT_RECORD asks for one (idempotent; no-op
    # unset). Launched ranks already enabled at import via their
    # launcher-assigned identity — this covers the standalone
    # no-launcher mode, and keeps the supervisor (which never inits a
    # runtime) from recording.
    from horovod_tpu import flight

    flight.enable()

    if registry.get_str(ENV_PLATFORM):
        jax.config.update("jax_platforms", registry.get_str(ENV_PLATFORM))
    n_cpu = registry.get_int(ENV_NUM_CPU_DEVICES)
    if n_cpu is not None:
        try:
            jax.config.update("jax_num_cpu_devices", n_cpu)
        except AttributeError:
            # Older jax: the config option doesn't exist. XLA_FLAGS works as
            # long as the backend hasn't initialized yet — true here for the
            # launched-child path (init() runs before any device use).
            # HVT_NUM_CPU_DEVICES is authoritative (the config-option
            # semantics), so an inherited device-count flag — e.g. the test
            # harness's 8-device XLA_FLAGS leaking into launched children —
            # is REPLACED, not kept: a 2-process fleet accidentally running
            # 8 virtual devices per process wedges its cross-process
            # collectives.
            import re as _re

            flags = os.environ.get("XLA_FLAGS", "")
            flags = _re.sub(
                r"--xla_force_host_platform_device_count=\d+", "", flags
            )
            os.environ["XLA_FLAGS"] = (
                flags.strip()
                + f" --xla_force_host_platform_device_count={n_cpu}"
            ).strip()
    if env_flag("HVT_FAST_RNG"):
        # TPU hardware RNG for dropout/init keys: threefry (the reproducible
        # default) costs real step time when dropout is on (~12% on the LM
        # bench); 'rbg' makes it free. Opt-in — rbg streams are not
        # bit-reproducible across topologies the way threefry is.
        jax.config.update("jax_default_prng_impl", "rbg")

    coordinator_address = coordinator_address or registry.get_str(ENV_COORDINATOR)
    if num_processes is None:
        num_processes = registry.get_int(ENV_NUM_PROCESSES)
    if process_id is None:
        process_id = registry.get_int(ENV_PROCESS_ID)

    if coordinator_address is not None:
        # Multi-process on the CPU *platform* (the launched test mode,
        # README.md:53-58): cross-process collectives need the gloo CPU
        # backend on jax versions where it isn't the default. Must land
        # before backend init — true here, init() precedes any device use.
        platform_hint = (
            registry.get_str(ENV_PLATFORM)
            or os.environ.get("JAX_PLATFORMS", "")
        )
        if "cpu" in platform_hint:
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except (AttributeError, ValueError):
                pass  # option absent (newer jax handles this itself)
        # Multi-host control plane over DCN: replaces MPI_Init + the Horovod
        # background coordinator thread (SURVEY.md §2.3 row 1) — after this,
        # collective order is compiled statically, no runtime negotiation.
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    else:
        # The gloo config above is process-global and STICKY: a reinit
        # back to single-process (a fleet evicted/shrunk down to one
        # survivor has no coordinator) would otherwise create the CPU
        # backend with collectives that demand the distributed client
        # torn down two lines ago.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "none")
        except (AttributeError, ValueError):
            pass
    _initialized = True
    return world()


def shutdown() -> None:
    """Tear down the distributed runtime (no-op if single-process).

    In a multi-process world this is a BARRIER: every process must call it
    at the same point, or the coordination service flags the stragglers'
    disconnect as a fatal error and terminates the survivors (see
    `compat.distributed_shutdown_barrier`). The elastic rescale path calls
    it from the membership-change agreement, where lockstep is guaranteed."""
    global _initialized
    if not _initialized:
        return
    try:
        if jax.process_count() > 1:
            from horovod_tpu import compat

            compat.distributed_shutdown_barrier()
    finally:
        _initialized = False


def reinit(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> World:
    """Tear down whatever runtime exists and initialize at a (possibly
    different) world size — the elastic rescale primitive.

    Sequence: synchronized distributed shutdown (if a world is up — all
    processes of the OLD world must arrive here together), then backend
    drop (old executables/arrays were compiled against the old collective
    world and are invalid — hold host copies, the `ElasticState.commit`
    contract), then a fresh `init` at the new size. With no coordinator
    the result is the bare single-process mode — a fleet shrunk to one
    survivor keeps training with every collective degraded to a local op."""
    global _initialized
    from horovod_tpu import compat

    shutdown()
    compat.reset_distributed_state()  # idempotent; covers a torn shutdown
    compat.clear_backends()
    _initialized = False
    return init(coordinator_address, num_processes, process_id)


def is_initialized() -> bool:
    return _initialized


def world() -> World:
    return World(
        process_rank=jax.process_index(),
        process_count=jax.process_count(),
        local_rank=local_rank(),
        device_count=jax.device_count(),
        local_device_count=jax.local_device_count(),
        hostname=socket.gethostname(),
        platform=jax.default_backend(),
    )


# --- Horovod-parity topology queries (SURVEY.md §2.4 row 2) ----------------


def rank() -> int:
    """Global rank for single-writer gating (≈ ``hvd.rank()``).

    Returns the process index: exactly one process in the job returns 0, so
    ``rank() == 0`` preserves the reference's rank-0-only checkpoint/log
    convention (tensorflow2_keras_mnist.py:86-92)."""
    return jax.process_index()


def size() -> int:
    """World size for LR scaling / work division (≈ ``hvd.size()``).

    Returns the total chip count — the degree of data parallelism — which is
    what `lr * size` (tensorflow2_keras_mnist.py:55) and `steps // size`
    (:96) must react to."""
    return jax.device_count()


def local_rank() -> int:
    """Ordinal of this process among co-located processes (≈ ``hvd.local_rank()``).

    0 in the standard one-process-per-host deployment; launchers that place
    several processes on one host set HVT_LOCAL_RANK. Note the reference uses
    this only for GPU pinning (mnist_keras.py:35), which has no TPU analogue."""
    return registry.get_int(ENV_LOCAL_RANK)


def local_size() -> int:
    """Number of chips driven by this process (≈ ``hvd.local_size()``)."""
    return jax.local_device_count()


def process_rank() -> int:
    """Explicit process-level rank (same as rank(); here for clarity)."""
    return jax.process_index()


def process_count() -> int:
    """Number of host processes in the job."""
    return jax.process_count()


def is_primary() -> bool:
    """True on exactly one process — the single writer for checkpoints,
    TensorBoard and exports (reference convention, mnist_keras.py:100-105)."""
    return jax.process_index() == 0
