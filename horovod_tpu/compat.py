"""jax version-tolerance shims.

The repo targets the current jax spelling of APIs; containers pinned to an
older jax (< 0.5) lack some of them. Every such difference is absorbed here
— call sites import from `horovod_tpu.compat` and stay on the modern
signature.

* ``shard_map``: ``jax.shard_map(..., check_vma=...)`` is the modern form;
  older releases ship ``jax.experimental.shard_map.shard_map`` whose
  equivalent knob is spelled ``check_rep``.
* ``axis_size``: ``jax.lax.axis_size(name)`` is newer; the portable
  spelling reads the bound axis env directly (a trace-time constant, like
  the modern call — NOT a ``psum(1)`` collective).
"""

from __future__ import annotations

import jax
from jax import lax

if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:

    def axis_size(axis_name) -> int:
        """Size of a bound mesh axis (tuple = product), trace-time."""
        if isinstance(axis_name, (tuple, list)):
            out = 1
            for n in axis_name:
                out *= axis_size(n)
            return out
        from jax._src import core as _core  # old jax only: no public API

        return _core.get_axis_env().axis_size(axis_name)

if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )

else:  # jax < 0.5: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )
