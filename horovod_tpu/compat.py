"""jax version-tolerance shims.

The repo targets the current jax spelling of APIs; containers pinned to an
older jax (< 0.5) lack some of them. Every such difference is absorbed here
— call sites import from `horovod_tpu.compat` and stay on the modern
signature.

* ``shard_map``: ``jax.shard_map(..., check_vma=...)`` is the modern form;
  older releases ship ``jax.experimental.shard_map.shard_map`` whose
  equivalent knob is spelled ``check_rep``.
* ``axis_size``: ``jax.lax.axis_size(name)`` is newer; the portable
  spelling reads the bound axis env directly (a trace-time constant, like
  the modern call — NOT a ``psum(1)`` collective).
"""

from __future__ import annotations

import jax
from jax import lax

if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:

    def axis_size(axis_name) -> int:
        """Size of a bound mesh axis (tuple = product), trace-time."""
        if isinstance(axis_name, (tuple, list)):
            out = 1
            for n in axis_name:
                out *= axis_size(n)
            return out
        from jax._src import core as _core  # old jax only: no public API

        return _core.get_axis_env().axis_size(axis_name)

if hasattr(jax, "shard_map"):

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )

else:  # jax < 0.5: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )


# --- elastic rescale shims (horovod_tpu.elastic) ---------------------------
#
# Resizing the world in-process needs two operations jax has no stable public
# API for: fully resetting the distributed runtime's global state (so a
# second `initialize()` is legal) and dropping the live backends (whose
# collectives are compiled against the OLD world size). Both touch private
# modules whose spelling drifts across versions — absorbed here.


def reset_distributed_state() -> None:
    """Null out jax's distributed global state so a subsequent
    ``jax.distributed.initialize`` succeeds.

    ``jax.distributed.shutdown()`` forgets ``preemption_sync_manager`` on
    0.4.x ("Preemption sync manager should only be initialized once" on the
    next init) and leaves ``coordinator_address``/``process_id`` populated;
    a rescale must clear everything. Attribute-tolerant: fields that a jax
    version lacks are skipped."""
    try:
        from jax._src import distributed
    except ImportError:  # pragma: no cover — future jax moved the module
        return
    state = distributed.global_state
    for attr in ("client", "service", "preemption_sync_manager",
                 "coordinator_address"):
        if hasattr(state, attr):
            setattr(state, attr, None)
    # Back to the PRISTINE single-process values, not None: backend
    # creation reads process_id/num_processes directly (node_id=None
    # crashes the CPU client constructor).
    if hasattr(state, "process_id"):
        state.process_id = 0
    if hasattr(state, "num_processes"):
        state.num_processes = 1


def distributed_shutdown_barrier() -> None:
    """The SYNCHRONIZED clean teardown of a live distributed world: every
    process must call this at the same point (a collective boundary).

    ``client.shutdown()`` is a barrier — it completes only when all tasks
    reach it, which is exactly what keeps the coordination service from
    entering its error state (an abrupt disconnect makes it propagate a
    fatal error to every surviving client — observed as SIGABRT,
    "Terminating process because the JAX distributed service detected
    fatal errors"). After the barrier, leftover fields are reset so
    re-initialization at a new world size is legal."""
    try:
        from jax._src import distributed
    except ImportError:  # pragma: no cover
        return
    state = distributed.global_state
    try:
        state.shutdown()
    finally:
        reset_distributed_state()


def clear_backends() -> None:
    """Drop live XLA backends (and jit caches) so the next device use
    re-creates them against the CURRENT distributed world.

    Every live ``jax.Array`` is invalidated — callers must hold host
    (numpy) copies of anything they still need (the ElasticState commit
    contract). Spelling drift: ``jax.extend.backend.clear_backends`` is the
    current home; older releases only have the underscored xla_bridge
    helper."""
    jax.clear_caches()
    try:
        from jax.extend import backend as _backend

        _backend.clear_backends()
        return
    except (ImportError, AttributeError):
        pass
    from jax._src import xla_bridge  # pragma: no cover — old jax only

    xla_bridge._clear_backends()
