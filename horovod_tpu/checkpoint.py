"""Checkpoint / resume / serving export — single-writer, TPU-native.

Parity surface (SURVEY.md §5.4): three artifacts, all primary-process-gated:

1. *Training checkpoints*: per-epoch full state (params + optimizer slots +
   step + rng) — the role of ``ModelCheckpoint('checkpoint-{epoch}.h5')``
   (tensorflow2_keras_mnist.py:86-88). One msgpack file via flax
   serialization; atomic rename so a crashed writer never leaves a torn file.
2. *Final model*: ``save(path, state)`` anywhere — role of
   ``model.save('keras-sample-model.h5')`` (mnist_keras.py:118-120).
3. *Serving export*: a **timestamped directory** (versioning convention kept,
   mnist_keras.py:126) holding serialized StableHLO of the jitted
   ``input → prob`` function plus the weights — role of TF1
   SavedModelBuilder with ``predict_signature_def(inputs={'input'},
   outputs={'prob'})`` (mnist_keras.py:126-140), without TF anywhere.

Resume is restore → broadcast: load on the primary, then
``broadcast_parameters`` syncs all processes (the reference's implicit resume
contract, tensorflow2_keras_mnist.py:68-71).
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any

import jax
import numpy as np
from flax import serialization

from horovod_tpu import runtime
from horovod_tpu.parallel import collectives, sharding

PyTree = Any

# Accept any extension so user-supplied templates ('checkpoint-{epoch}.h5',
# Keras-style) are still discovered on resume.
CHECKPOINT_RE = re.compile(r"checkpoint-(\d+)\.\w+$")


def save(path: str, state: PyTree) -> str:
    """Serialize a state pytree to one file, atomically. Caller gates rank
    (callbacks do; direct users should check ``runtime.is_primary()``)."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    data = serialization.to_bytes(jax.device_get(state))
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)  # atomic: no torn checkpoints on crash (§5.2)
    return path


class _SaveThread:
    """Background save handle whose `join()` re-raises the thread's failure —
    a checkpoint that silently failed to write must not look successful."""

    def __init__(self, work):
        import threading

        self.exc: BaseException | None = None

        def run():
            try:
                work()
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                self.exc = e

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def join(self, timeout=None):
        self._t.join(timeout)
        if self.exc is not None:
            raise self.exc

    def is_alive(self):
        return self._t.is_alive()


def save_async(path: str, state: PyTree) -> _SaveThread:
    """`save` without blocking the training loop.

    The state is first copied ON DEVICE (cheap, and immune to the training
    step's buffer donation — the live state's buffers are consumed by the
    next step), then the host fetch + serialization + atomic write run on a
    daemon thread. Returns a handle; `join()` it (or let
    `callbacks.ModelCheckpoint(async_save=True)` manage ordering) before
    reading the file — join re-raises any write failure.

    Multi-process safe for the replicated (DP) state this framework
    checkpoints: fully-replicated leaves are snapshot from one local shard
    (no cross-process computation may run on the primary alone)."""
    import jax.numpy as jnp

    def snap(a):
        if isinstance(a, jax.Array) and a.is_fully_replicated:
            # Local-shard copy: an eager global jnp.copy would be a
            # collective computation only the primary enters (deadlock/error
            # in multi-process runs).
            return jnp.copy(a.addressable_data(0))
        return jnp.copy(a)

    snapshot = jax.tree.map(snap, state)
    return _SaveThread(lambda: save(path, snapshot))


def restore(path: str, template: PyTree) -> PyTree:
    """Deserialize into the structure of ``template``."""
    with open(path, "rb") as f:
        data = f.read()
    return serialization.from_bytes(jax.device_get(template), data)


def save_checkpoint(directory: str, state: PyTree, epoch: int) -> str:
    """Epoch-numbered checkpoint (``checkpoint-{epoch}.msgpack``), parity
    with the reference's per-epoch template (tensorflow2_keras_mnist.py:87).
    Epochs are 1-based (epoch 0 means "no checkpoint" on resume)."""
    return save(os.path.join(directory, f"checkpoint-{epoch}.msgpack"), state)


def latest_checkpoint(directory: str) -> str | None:
    """Highest-epoch checkpoint path, or None."""
    if not os.path.isdir(directory):
        return None
    best, best_epoch = None, -1
    for name in os.listdir(directory):
        m = CHECKPOINT_RE.search(name)
        if m and int(m.group(1)) > best_epoch:
            best_epoch = int(m.group(1))
            best = os.path.join(directory, name)
    return best


def _host_syncable(leaf) -> bool:
    """Whether a leaf's value can be host-gathered on any single process:
    non-arrays, process-local arrays, and fully-REPLICATED global arrays
    (device_get special-cases those even when they span hosts). Only arrays
    genuinely SHARDED across processes (model-parallel stage/TP/FSDP shards)
    are excluded — they cannot be gathered from one process and need no sync
    either: every process materialized them from the same deterministic SPMD
    init program."""
    return (
        not isinstance(leaf, jax.Array)
        or leaf.is_fully_replicated
        or leaf.is_fully_addressable
    )


def broadcast_parameters(tree: PyTree, root_rank: int = 0, mesh=None) -> PyTree:
    """``hvd.broadcast_global_variables(0)`` equivalent for any pytree:
    every process adopts the root's values; with ``mesh`` given,
    host-syncable leaves are re-placed replicated on the mesh, and with
    ``mesh=None`` each leaf keeps its own sharding. Leaves sharded across
    processes are left untouched (see `_host_syncable`)."""
    if jax.process_count() > 1:
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        idx = [i for i, l in enumerate(leaves) if _host_syncable(l)]
        synced = collectives.broadcast_pytree(
            [jax.device_get(leaves[i]) for i in idx], root=root_rank
        )
        for i, host_val in zip(idx, synced):
            old = leaves[i]
            if isinstance(old, jax.Array) and mesh is None:
                leaves[i] = jax.device_put(host_val, old.sharding)
            else:
                leaves[i] = host_val
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
    if mesh is not None:
        tree = jax.tree.map(
            lambda l: jax.device_put(l, sharding.replicated(mesh))
            if _host_syncable(l)
            else l,
            tree,
        )
    return tree


def restore_latest_and_broadcast(directory: str, template: PyTree, mesh=None) -> tuple[PyTree, int]:
    """The full resume path (§5.3): the primary loads the newest checkpoint,
    all processes adopt it. Returns (state, epoch) — epoch 0 if none found.

    Collective-safe under single-writer checkpoints: only the *primary's*
    view of the directory decides (checkpoints may exist on its filesystem
    only), and that decision is broadcast first so every process takes the
    same branch — no process can skip a collective the others entered."""
    primary = runtime.is_primary()
    path = latest_checkpoint(directory) if primary else None
    epoch = int(CHECKPOINT_RE.search(path).group(1)) if path else 0
    if jax.process_count() > 1:
        epoch = int(collectives.broadcast(np.int64(epoch), root=0))
    if epoch == 0:
        return template, 0
    state = restore(path, template) if primary else template
    return broadcast_parameters(state, mesh=mesh), epoch


# --- Serving export (TF-free SavedModel role) ------------------------------

SIGNATURE_FILE = "signature.json"
GRAPH_FILE = "model.stablehlo"
WEIGHTS_FILE = "weights.msgpack"


def export_serving(
    export_dir: str,
    apply_fn,
    params: PyTree,
    input_shape: tuple,
    input_dtype=np.float32,
    timestamp: str | None = None,
) -> str:
    """Export a serving bundle into ``export_dir/<YYYYmmdd-HHMMSS>/``.

    ``apply_fn(params, x)`` must return logits; the exported program is the
    jitted ``x → softmax(logits)`` closure over the weights, serialized as
    portable StableHLO via `jax.export` — the TPU-native stand-in for the TF1
    SavedModel with signature ``{'input' → 'prob'}`` (mnist_keras.py:126-140).
    Primary-process-only by convention (caller script gates, like the
    reference's ``if hvd.rank() == 0``)."""
    from jax import export as jax_export

    stamp = timestamp or time.strftime("%Y%m%d-%H%M%S")
    out_dir = os.path.join(export_dir, stamp)
    os.makedirs(out_dir, exist_ok=True)

    def predict(x):
        return jax.nn.softmax(apply_fn(params, x), axis=-1)

    spec = jax.ShapeDtypeStruct(input_shape, input_dtype)
    exported = jax_export.export(jax.jit(predict))(spec)
    with open(os.path.join(out_dir, GRAPH_FILE), "wb") as f:
        f.write(exported.serialize())
    with open(os.path.join(out_dir, WEIGHTS_FILE), "wb") as f:
        f.write(serialization.to_bytes(jax.device_get(params)))
    with open(os.path.join(out_dir, SIGNATURE_FILE), "w") as f:
        json.dump(
            {
                "signature": {"inputs": {"input": {"shape": list(input_shape),
                                                   "dtype": np.dtype(input_dtype).name}},
                              "outputs": {"prob": {}}},
                "format": "stablehlo+msgpack",
                "created": stamp,
            },
            f,
            indent=2,
        )
    return out_dir


def load_serving(bundle_dir: str):
    """Reload an exported bundle; returns ``fn(input) -> prob``."""
    from jax import export as jax_export

    with open(os.path.join(bundle_dir, GRAPH_FILE), "rb") as f:
        exported = jax_export.deserialize(f.read())
    return lambda x: exported.call(x)
