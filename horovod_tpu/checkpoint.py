"""Checkpoint / resume / serving export — single-writer, TPU-native.

Parity surface (SURVEY.md §5.4): three artifacts, all primary-process-gated:

1. *Training checkpoints*: per-epoch full state (params + optimizer slots +
   step + rng) — the role of ``ModelCheckpoint('checkpoint-{epoch}.h5')``
   (tensorflow2_keras_mnist.py:86-88). One msgpack file via flax
   serialization; atomic rename so a crashed writer never leaves a torn file.
2. *Final model*: ``save(path, state)`` anywhere — role of
   ``model.save('keras-sample-model.h5')`` (mnist_keras.py:118-120).
3. *Serving export*: a **timestamped directory** (versioning convention kept,
   mnist_keras.py:126) holding serialized StableHLO of the jitted
   ``input → prob`` function plus the weights — role of TF1
   SavedModelBuilder with ``predict_signature_def(inputs={'input'},
   outputs={'prob'})`` (mnist_keras.py:126-140), without TF anywhere.

Resume is restore → broadcast: load on the primary, then
``broadcast_parameters`` syncs all processes (the reference's implicit resume
contract, tensorflow2_keras_mnist.py:68-71).

**Integrity**: every checkpoint file (single-file payloads AND per-process
shard files) gets a ``.sha256`` sidecar written right after its atomic
rename. Discovery (`latest_checkpoint`/`_sharded_complete`) and restore
verify it, so a checkpoint corrupted after landing — torn fsync, bit rot,
a truncated shard — is skipped in favor of the previous complete epoch
rather than deserialized into garbage. Files without a sidecar (pre-digest
checkpoints) are accepted unverified.

**Sharded (distributed) checkpoints**: when the state is sharded ACROSS
processes (pipeline stages, cross-host TP/FSDP), no single process can
host-gather it, so the single-file format is impossible. The sharded format
is a ``checkpoint-{epoch}.shards/`` directory: every process writes exactly
its addressable replica-0 shards (one ``shard-{p}.msgpack`` each — no
communication), the primary writes ``index.json``, and completeness (index +
all per-process files present) is validated at discovery time so a
checkpoint torn by mid-write failure is skipped in favor of the newest
complete one. Restore is also process-local: each process reads the shard
bytes its template shardings need and re-places them with
`jax.make_array_from_single_device_arrays`. Requires a filesystem all
processes share — the same assumption the reference's ``PS_MODEL_PATH``
persistent mount makes (tensorflow2_keras_mnist.py:21-22).
`save_checkpoint`/`ModelCheckpoint`/`restore_latest_and_broadcast` pick the
format automatically from the state's shardings.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import re
import time
from typing import Any

import jax
import numpy as np
from flax import serialization

from horovod_tpu import runtime
from horovod_tpu.parallel import collectives, sharding

PyTree = Any

# Accept any extension so user-supplied templates ('checkpoint-{epoch}.h5',
# Keras-style) are still discovered on resume. Deliberately does NOT match
# digest sidecars (extra '.sha256' after the extension).
CHECKPOINT_RE = re.compile(r"checkpoint-(\d+)\.\w+$")

# Progress manifest: '<file>.meta.json' records the (epoch, step) a
# checkpoint resumes at — STEP-granular, so a mid-epoch save (ModelCheckpoint
# save_every_steps) relaunches with fit(initial_epoch=epoch, initial_step=
# step) instead of replaying the whole epoch. The meta also records the
# payload's sha256: a crash between the payload's atomic replace and the
# meta's leaves a stale meta whose digest no longer matches, and
# `checkpoint_progress` then falls back to (filename epoch, step 0) — a
# full-epoch replay, never a wrong weights/step pairing. Sharded
# checkpoints carry the same record in index.json ("progress").
META_SUFFIX = ".meta.json"

# Integrity sidecar: '<file>.sha256' holds the hex digest of '<file>'.
# Written right after the payload's atomic rename; verified on discovery
# and restore, so a checkpoint corrupted AFTER its atomic write landed (a
# writer killed mid-fsync on a lying filesystem, a flipped bit, a truncated
# shard) is skipped in favor of the previous complete one instead of being
# deserialized into garbage. Files without a sidecar (pre-digest
# checkpoints) are accepted unverified for backward compatibility.
DIGEST_SUFFIX = ".sha256"


class CheckpointCorruptError(ValueError):
    """A checkpoint file's bytes do not match its recorded sha256 digest."""


_write_seq = itertools.count()


def _atomic_write(path: str, data: bytes, digest: bool = False) -> None:
    # Unique per WRITE, not just per process: a pid-only suffix collides
    # when two same-process writers target one path concurrently (e.g. an
    # async ModelCheckpoint save in flight while PreemptionCheckpoint
    # sync-saves the same epoch) and their interleaved writes would be
    # os.replace'd into place as a corrupt checkpoint. With distinct temp
    # files, each replace installs one complete payload — last wins.
    tmp = f"{path}.tmp.{os.getpid()}.{next(_write_seq)}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)  # atomic: no torn checkpoints on crash (§5.2)
    if digest:
        # Sidecar lands after the payload; both writes are atomic. The
        # crash window between them leaves a payload with a missing/stale
        # sidecar — safe either way: missing = legacy-accept, stale =
        # only reachable when two writers raced the SAME path, and those
        # write identical bytes (same committed state, same epoch), so
        # the digest still matches.
        dtmp = f"{path}{DIGEST_SUFFIX}.tmp.{os.getpid()}.{next(_write_seq)}"
        with open(dtmp, "w") as f:
            f.write(hashlib.sha256(data).hexdigest() + "\n")
        os.replace(dtmp, path + DIGEST_SUFFIX)


def recorded_digest(path: str) -> str | None:
    """The sidecar-recorded sha256 hex digest for ``path``, or None when no
    sidecar exists (a pre-digest checkpoint — accepted unverified)."""
    try:
        with open(path + DIGEST_SUFFIX) as f:
            return f.read().strip() or None
    except OSError:
        return None


def file_intact(path: str) -> bool:
    """True when ``path``'s bytes match its recorded digest (or no digest
    was recorded). False on mismatch or an unreadable file."""
    want = recorded_digest(path)
    if want is None:
        return os.path.isfile(path)
    h = hashlib.sha256()
    try:
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
    except OSError:
        return False
    return h.hexdigest() == want


def _read_verified(path: str) -> bytes:
    """Read a checkpoint file and verify it against its digest sidecar —
    the restore-side half of the integrity contract (discovery uses
    `file_intact`; both must hold so a corrupt file neither loads as
    garbage nor wins discovery)."""
    with open(path, "rb") as f:
        data = f.read()
    want = recorded_digest(path)
    if want is not None and hashlib.sha256(data).hexdigest() != want:
        raise CheckpointCorruptError(
            f"checkpoint file {path} does not match its recorded sha256 "
            "digest — the file was corrupted after being written (torn "
            "write, bit rot, or a concurrent writer). Delete it to fall "
            "back to the previous complete checkpoint."
        )
    return data


def save(path: str, state: PyTree, progress: tuple | None = None,
         cursor: dict | None = None) -> str:
    """Serialize a state pytree to one file, atomically. Caller gates rank
    (callbacks do; direct users should check ``runtime.is_primary()``).

    ``progress=(epoch, step)`` additionally writes the ``.meta.json``
    progress manifest: the resume point this checkpoint represents, at
    OPTIMIZER-step granularity (step 0 = an epoch boundary). The manifest
    records the payload's sha256 so a torn save can never pair fresh
    weights with a stale step (see `checkpoint_progress`).

    ``cursor`` (a `data.stream.StreamCursor` dict — the
    `Trainer.stream_cursor` record) rides inside the manifest: the
    DURABLE data-stream position this checkpoint resumes at, including
    the stream-format version, so `checkpoint_cursor` can refuse a
    cursor from an incompatible stream derivation loudly instead of
    silently re-anchoring the byte stream.

    Refuses cross-process-sharded state loudly: no single process holds it,
    so a one-file checkpoint is impossible — use `save_sharded` (the
    `save_checkpoint`/`ModelCheckpoint` paths route there automatically)."""
    if is_cross_process_sharded(state):
        raise ValueError(
            "state contains arrays sharded across processes (model-parallel "
            "leaves); a single-file checkpoint cannot represent them. Use "
            "checkpoint.save_sharded(path, state) from every process — "
            "save_checkpoint/ModelCheckpoint select it automatically."
        )
    from horovod_tpu import trace

    with trace.span("checkpoint_save", path=os.path.basename(path)):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        data = serialization.to_bytes(jax.device_get(state))
        _atomic_write(path, data, digest=True)
        if progress is not None:
            epoch, step = progress
            meta = {
                "epoch": int(epoch), "step": int(step),
                "payload_sha256": hashlib.sha256(data).hexdigest(),
            }
            if cursor is not None:
                meta["cursor"] = dict(cursor)
            _atomic_write(path + META_SUFFIX, json.dumps(meta).encode())
    return path


class _SaveThread:
    """Background save handle whose `join()` — and `is_alive()`, once the
    thread has finished — re-raise the thread's failure: a checkpoint that
    silently failed to write must not look successful. The exception is
    kept (not consumed), so every later consumption point re-raises too —
    `ModelCheckpoint` hits it at the next epoch's join and again at train
    end, whichever the caller reaches first."""

    def __init__(self, work):
        import threading

        self.exc: BaseException | None = None

        def run():
            try:
                work()
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                self.exc = e

        self._t = threading.Thread(target=run, daemon=True)
        self._t.start()

    def join(self, timeout=None):
        self._t.join(timeout)
        if self.exc is not None:
            raise self.exc

    def is_alive(self):
        alive = self._t.is_alive()
        if not alive and self.exc is not None:
            # A caller polling is_alive() instead of joining must not read
            # "finished" as "succeeded" — the failure surfaces here too.
            raise self.exc
        return alive


def save_async(path: str, state: PyTree,
               progress: tuple | None = None,
               cursor: dict | None = None) -> _SaveThread:
    """`save` without blocking the training loop.

    The state is first copied ON DEVICE (cheap, and immune to the training
    step's buffer donation — the live state's buffers are consumed by the
    next step), then the host fetch + serialization + atomic write run on a
    daemon thread. Returns a handle; `join()` it (or let
    `callbacks.ModelCheckpoint(async_save=True)` manage ordering) before
    reading the file — join re-raises any write failure.

    Multi-process safe for the replicated (DP) state this framework
    checkpoints: fully-replicated leaves are snapshot from one local shard
    (no cross-process computation may run on the primary alone)."""
    import jax.numpy as jnp

    if is_cross_process_sharded(state):
        # Same loud refusal as `save`, but BEFORE the snapshot: the primary-
        # only caller this function documents would otherwise hit a cryptic
        # non-fully-addressable-array error (or desync its peers) right here
        # on the caller thread, never reaching save()'s message at join().
        raise ValueError(
            "state contains arrays sharded across processes; use "
            "checkpoint.save_sharded_async(path, state) from every process "
            "— ModelCheckpoint(async_save=True) selects it automatically."
        )

    def snap(a):
        if isinstance(a, jax.Array) and a.is_fully_replicated:
            # Local-shard copy: an eager global jnp.copy would be a
            # collective computation only the primary enters (deadlock/error
            # in multi-process runs).
            return jnp.copy(a.addressable_data(0))
        return jnp.copy(a)

    snapshot = jax.tree.map(snap, state)
    return _SaveThread(
        lambda: save(path, snapshot, progress=progress, cursor=cursor)
    )


def restore(path: str, template: PyTree, *, reshard: bool = False) -> PyTree:
    """Deserialize into the structure of ``template``. A directory path is a
    sharded checkpoint and routes to `restore_sharded` (``reshard`` as
    there). The file is verified against its digest sidecar when one exists
    (`CheckpointCorruptError` on mismatch — never deserialize garbage)."""
    if os.path.isdir(path):
        return restore_sharded(path, template, reshard=reshard)
    return serialization.from_bytes(
        jax.device_get(template), _read_verified(path)
    )


# --- Sharded (distributed) checkpoint format -------------------------------

SHARDED_SUFFIX = ".shards"
INDEX_FILE = "index.json"


def is_cross_process_sharded(tree: PyTree) -> bool:
    """True when any leaf is sharded across processes — the condition under
    which checkpoints must use the sharded directory format."""
    return any(
        isinstance(l, jax.Array) and not _host_syncable(l)
        for l in jax.tree.leaves(tree)
    )


def _fmt_index(index: tuple, shape: tuple) -> str:
    """Canonical key for one shard's position in its global array:
    ``'0:64,0:128'`` start:stop per dimension (empty string for scalars)."""
    parts = []
    for s, dim in zip(index, shape):
        start, stop, step = s.indices(dim)
        if step != 1:
            raise ValueError(f"strided shard index unsupported: {index}")
        parts.append(f"{start}:{stop}")
    return ",".join(parts)


def leaf_shard_pieces(leaf) -> dict:
    """This process's OWNED pieces of one array leaf: ``{index_spec:
    np.ndarray}`` over the addressable shards with ``replica_id == 0`` —
    the dedup under which every piece of the global array is held by
    exactly one process fleet-wide. The single extraction shared by
    `save_sharded`, `gather_to_host`, and the elastic per-shard commit
    (`horovod_tpu.elastic.ElasticState`)."""
    return {
        _fmt_index(sh.index, leaf.shape): np.asarray(sh.data)
        for sh in leaf.addressable_shards
        if sh.replica_id == 0
    }


def save_sharded(path: str, state: PyTree,
                 progress: tuple | None = None,
                 cursor: dict | None = None) -> str:
    """Distributed checkpoint: EVERY process calls this (unlike `save`).

    Each process writes one ``shard-{p}.msgpack`` holding exactly the shard
    bytes it is the owner of — its addressable shards with ``replica_id ==
    0``, so each piece of the global state is stored once fleet-wide and
    replicated leaves cost one copy, not ``n_processes``. No communication
    happens: save never deadlocks and tolerates peers dying mid-write (the
    torn checkpoint simply never validates as complete). The primary also
    writes ``index.json`` recording the expected file count plus every
    leaf's tree path (restore validates them — shard keys are positional, so
    without names a same-shape rename/reorder would restore silently
    swapped); host-side (non-array) leaves go in the primary's shard
    file."""
    from horovod_tpu import trace

    paths_and_leaves, _ = jax.tree_util.tree_flatten_with_path(state)
    leaves = [l for _, l in paths_and_leaves]
    os.makedirs(path, exist_ok=True)
    payload = {}
    with trace.span("checkpoint_save", path=os.path.basename(path),
                    sharded=True):
        for i, leaf in enumerate(leaves):
            if isinstance(leaf, jax.Array):
                for spec, piece in leaf_shard_pieces(leaf).items():
                    payload[f"{i}|{spec}"] = piece
            elif runtime.is_primary():
                payload[f"{i}|host"] = np.asarray(leaf)
        _atomic_write(
            os.path.join(path, f"shard-{jax.process_index()}.msgpack"),
            serialization.msgpack_serialize(payload),
            digest=True,
        )
    if runtime.is_primary():
        index = {
            "format": 1,
            "n_processes": jax.process_count(),
            "leaf_count": len(leaves),
            "leaf_names": [
                jax.tree_util.keystr(p) for p, _ in paths_and_leaves
            ],
        }
        if progress is not None:
            # The (epoch, step) resume point this checkpoint represents —
            # the sharded twin of the single-file .meta.json manifest.
            index["progress"] = {
                "epoch": int(progress[0]), "step": int(progress[1]),
            }
        if cursor is not None:
            # The durable data-stream cursor (sharded twin of the
            # .meta.json "cursor" record — see `save`).
            index["cursor"] = dict(cursor)
        # digest=True: the index gets its own .sha256 sidecar like every
        # payload file — a bit-rotted index would otherwise misdirect the
        # whole restore (wrong n_processes tears discovery; corrupted
        # leaf_names could mis-verify structure) while every shard file
        # still verified clean.
        _atomic_write(
            os.path.join(path, INDEX_FILE), json.dumps(index).encode(),
            digest=True,
        )
    return path


def save_sharded_async(path: str, state: PyTree,
                       progress: tuple | None = None,
                       cursor: dict | None = None) -> _SaveThread:
    """`save_sharded` off the training loop: snapshot every array leaf on
    device (buffer-donation immunity, same rationale as `save_async` — the
    copy is a communication-free SPMD identity every process enters), then
    write this process's shard file on a daemon thread."""
    import jax.numpy as jnp

    snapshot = jax.tree.map(
        lambda a: jnp.copy(a) if isinstance(a, jax.Array) else a, state
    )
    return _SaveThread(
        lambda: save_sharded(path, snapshot, progress=progress, cursor=cursor)
    )


def _sharded_complete(path: str) -> bool:
    """A sharded checkpoint is usable iff the index and every per-process
    shard file landed (each lands atomically) AND every file — the index
    included — still matches its recorded digest, so anything corrupted
    after landing loses discovery to the previous complete epoch exactly
    like a missing file (indexes without a sidecar are legacy-accepted,
    same as payloads)."""
    ipath = os.path.join(path, INDEX_FILE)
    if not file_intact(ipath):
        return False
    try:
        with open(ipath) as f:
            n = int(json.load(f)["n_processes"])
    except (OSError, ValueError, KeyError):
        return False
    return all(
        file_intact(os.path.join(path, f"shard-{p}.msgpack"))
        for p in range(n)
    )


def _parse_slices(spec: str) -> tuple:
    """Inverse of `_fmt_index`: ``'0:64,0:128'`` → (slice(0,64), slice(0,128))
    (the empty string is a scalar's index, ())."""
    if not spec:
        return ()
    return tuple(
        slice(int(a), int(b))
        for a, b in (part.split(":") for part in spec.split(","))
    )


def _assemble_global(store: dict, i: int, shape: tuple, dtype) -> np.ndarray:
    """Reassemble leaf ``i``'s full global array from whatever shard pieces
    the checkpoint holds (each global piece is stored exactly once —
    `save_sharded`'s replica_id==0 dedup — so the pieces tile the array)."""
    prefix = f"{i}|"
    arr = np.empty(shape, dtype)
    filled = 0
    for key, val in store.items():
        if not key.startswith(prefix) or key == f"{i}|host":
            continue
        piece = np.asarray(val)
        arr[_parse_slices(key[len(prefix):])] = piece
        filled += piece.size
    if filled != arr.size:
        raise ValueError(
            f"leaf {i}: shard pieces cover {filled} of {arr.size} elements — "
            "the checkpoint is torn or was saved with a different model size"
        )
    return arr


def restore_sharded(path: str, template: PyTree, *,
                    reshard: bool = False) -> PyTree:
    """Rebuild a sharded checkpoint onto the ``template``'s shardings.

    EVERY process calls this. Shard files are read lazily, own-process first:
    with an unchanged topology a process touches only its own file plus
    whichever file owns the replicated leaves. Each needed piece is
    device_put to its target device and the global arrays assembled with
    `jax.make_array_from_single_device_arrays` — no collective traffic.

    ``reshard=True`` lifts the same-topology requirement: a checkpoint saved
    under ANY process count / mesh / sharding layout restores onto the
    template's (train on pipe=2, fine-tune on data=4; shrink a pod; move a
    TP=4 model to TP=2 — the durability side of elasticity). Every process
    then reads all shard files, reassembles each mismatched leaf's global
    array on host, and re-slices it for its own devices; exact-layout leaves
    still take the piece-by-piece fast path. Costs one host-RAM copy of the
    largest leaf; leave False (the default) to keep topology drift loud on
    ordinary resumes."""
    # Digest-verified like every shard read below: a corrupt index must
    # raise CheckpointCorruptError, not steer the restore with garbage.
    index = json.loads(_read_verified(os.path.join(path, INDEX_FILE)))
    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = [l for _, l in paths_and_leaves]
    if len(leaves) != index["leaf_count"]:
        raise ValueError(
            f"checkpoint {path} holds {index['leaf_count']} leaves but the "
            f"template has {len(leaves)} — model/optimizer structure changed"
        )
    names = [jax.tree_util.keystr(p) for p, _ in paths_and_leaves]
    if names != index["leaf_names"]:
        drift = [
            f"{a!r} -> {b!r}"
            for a, b in zip(index["leaf_names"], names)
            if a != b
        ]
        raise ValueError(
            f"checkpoint {path} leaf names differ from the template's "
            f"(shard keys are positional, so this would restore the wrong "
            f"weights): {', '.join(drift[:5])} — model/optimizer structure "
            "changed"
        )
    if index["n_processes"] != jax.process_count() and not reshard:
        # Every process reads the same index, so all ranks raise together —
        # a partial-restore desync (some ranks proceeding into collectives
        # while others crash on a missing shard file) cannot happen.
        raise ValueError(
            f"checkpoint {path} was saved by {index['n_processes']} "
            f"processes but this run has {jax.process_count()} — sharded "
            "checkpoints resume only under the same process topology "
            "(pass reshard=True to re-slice onto the new one)"
        )
    me = jax.process_index()
    read_order = [p for p in range(index["n_processes"]) if p != me]
    if me < index["n_processes"]:
        read_order = [me] + read_order
    store: dict[str, np.ndarray] = {}

    class _ShardKeyMissing(ValueError):
        """Key absent after draining every shard file — the one condition
        the reshard fallback may treat as a layout mismatch (a corrupt
        file's own error must propagate, not be misread as 'resharding
        needed')."""

    def lookup(key):
        while key not in store and read_order:
            p = read_order.pop(0)
            store.update(serialization.msgpack_restore(
                _read_verified(os.path.join(path, f"shard-{p}.msgpack"))
            ))
        if key not in store:
            raise _ShardKeyMissing(
                f"shard {key!r} not found in {path}: the checkpoint was "
                "saved under a different mesh or sharding layout than the "
                "template's (resume must use the same parallel config, or "
                "pass reshard=True)"
            )
        return store[key]

    out = []
    for i, leaf in enumerate(leaves):
        if not isinstance(leaf, jax.Array):
            out.append(lookup(f"{i}|host"))
            continue
        target, shape = leaf.sharding, leaf.shape
        placement = target.addressable_devices_indices_map(shape).items()
        try:
            pieces = [
                jax.device_put(
                    np.asarray(
                        lookup(f"{i}|{_fmt_index(idx, shape)}"), leaf.dtype
                    ),
                    d,
                )
                for d, idx in placement
            ]
        except _ShardKeyMissing:
            if not reshard:
                raise
            # Saved layout ≠ template layout for this leaf: reassemble the
            # global array from all stored pieces and slice out what each
            # local device needs. `lookup` has already drained every shard
            # file into `store` before concluding a key is missing.
            whole = _assemble_global(store, i, shape, leaf.dtype)
            pieces = [
                # reshape: ascontiguousarray promotes 0-d slices to (1,).
                jax.device_put(
                    np.ascontiguousarray(whole[idx]).reshape(
                        np.shape(whole[idx])
                    ),
                    d,
                )
                for d, idx in placement
            ]
        out.append(
            jax.make_array_from_single_device_arrays(shape, target, pieces)
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def save_checkpoint(directory: str, state: PyTree, epoch: int,
                    step: int = 0, cursor: dict | None = None) -> str:
    """Epoch-numbered checkpoint (``checkpoint-{epoch}.msgpack``), parity
    with the reference's per-epoch template (tensorflow2_keras_mnist.py:87).
    Epochs are 1-based (epoch 0 means "no checkpoint" on resume).
    ``step`` > 0 marks a MID-epoch save: ``epoch`` is then the number of
    COMPLETED epochs and the manifest records the ``(epoch, step)`` resume
    point — `restore_latest_and_broadcast(with_step=True)` hands it back
    for ``fit(initial_epoch=, initial_step=)``.
    Cross-process-sharded state routes to the sharded directory format
    (``checkpoint-{epoch}.shards/``) — then ALL processes must call this."""
    if is_cross_process_sharded(state):
        return save_sharded(
            os.path.join(directory, f"checkpoint-{epoch}{SHARDED_SUFFIX}"),
            state, progress=(epoch, step), cursor=cursor,
        )
    return save(
        os.path.join(directory, f"checkpoint-{epoch}.msgpack"), state,
        progress=(epoch, step), cursor=cursor,
    )


def checkpoint_intact(path: str) -> bool:
    """Whether a discovered checkpoint artifact is safe to restore: a
    sharded dir must be complete with every shard matching its digest; a
    single file must match its digest sidecar (no sidecar = legacy,
    accepted)."""
    if os.path.isdir(path):
        return _sharded_complete(path)
    return file_intact(path)


def checkpoint_progress(path: str) -> tuple[int, int]:
    """The ``(epoch, step)`` resume point a checkpoint artifact records —
    step-granular when a progress manifest exists, ``(filename epoch, 0)``
    otherwise (pre-manifest checkpoints, or a manifest whose recorded
    payload sha256 no longer matches the payload: a crash landed the
    payload but not its manifest, and trusting the stale step would pair
    fresh weights with an old data position — a full-epoch replay from
    the filename epoch is the safe degradation)."""
    m = CHECKPOINT_RE.search(os.path.basename(path))
    fallback = (int(m.group(1)) if m else 0, 0)
    try:
        if os.path.isdir(path):
            with open(os.path.join(path, INDEX_FILE)) as f:
                rec = json.load(f).get("progress")
            if not rec:
                return fallback
            return int(rec["epoch"]), int(rec["step"])
        with open(path + META_SUFFIX) as f:
            rec = json.load(f)
        want = rec.get("payload_sha256")
        if want is not None:
            actual = recorded_digest(path)
            if actual is None:
                h = hashlib.sha256()
                with open(path, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        h.update(chunk)
                actual = h.hexdigest()
            if actual != want:
                return fallback
        return int(rec["epoch"]), int(rec["step"])
    except (OSError, ValueError, KeyError):
        return fallback


def checkpoint_cursor(path: str):
    """The durable data-stream cursor a checkpoint artifact records
    (`data.stream.StreamCursor`), or None when the artifact predates
    cursors / recorded none. A PRESENT cursor with an incompatible
    format version raises `stream.StreamCursorError` LOUDLY — the
    anchored-stream derivation changed, so honouring the recorded
    position would silently resume a different byte stream; the caller
    must degrade to epoch-granular resume explicitly (the progress
    manifest stays readable via `checkpoint_progress`), never guess."""
    from horovod_tpu.data import stream as stream_lib

    try:
        if os.path.isdir(path):
            with open(os.path.join(path, INDEX_FILE)) as f:
                rec = json.load(f).get("cursor")
        else:
            with open(path + META_SUFFIX) as f:
                rec = json.load(f).get("cursor")
    except (OSError, ValueError):
        return None
    if rec is None:
        return None
    return stream_lib.StreamCursor.from_dict(rec)


def latest_checkpoint(directory: str, *,
                      complete_only: bool = False) -> str | None:
    """Highest-epoch INTACT checkpoint path, or None. Sharded dirs count
    only when complete, and digest-verified files only when their bytes
    still match (`checkpoint_intact`) — so a checkpoint torn by a crash
    mid-save OR corrupted after landing loses to the previous epoch's
    complete one instead of being restored as garbage. Candidates are
    checked newest-first and only until one passes, so the common
    nothing-is-corrupt resume hashes exactly one checkpoint.

    ``complete_only=True`` additionally skips artifacts whose progress
    manifest records a MID-epoch step (`checkpoint_progress` step > 0) —
    the resolution step-UNaware resume takes
    (`restore_latest_and_broadcast` without ``with_step``): a mid-epoch
    save (``HVT_SAVE_EVERY_STEPS``) holds weights that already trained an
    epoch prefix, so a caller that resumes with ``fit(initial_epoch=)``
    alone must fall back to the newest COMPLETE-epoch checkpoint rather
    than silently re-apply that prefix's data to weights that consumed
    it."""
    if not os.path.isdir(directory):
        return None
    candidates = []
    for name in os.listdir(directory):
        m = CHECKPOINT_RE.search(name)
        if m:
            candidates.append((int(m.group(1)), os.path.join(directory, name)))
    for _, full in sorted(candidates, reverse=True):
        if checkpoint_intact(full):
            if complete_only and checkpoint_progress(full)[1] > 0:
                continue
            return full
    return None


def _torn_sharded_dirs(directory: str) -> list:
    """Sharded checkpoint dirs that never validated as complete. One can be
    a crash mid-save; ONLY torn ones across all epochs is the signature of a
    rank-gated saver (e.g. ``if rank == 0: ModelCheckpoint(...)`` — valid for
    replicated state, wrong for the sharded format, where EVERY process must
    write its shard file)."""
    if not os.path.isdir(directory):
        return []
    return sorted(
        os.path.join(directory, name)
        for name in os.listdir(directory)
        if CHECKPOINT_RE.search(name)
        and os.path.isdir(os.path.join(directory, name))
        and not _sharded_complete(os.path.join(directory, name))
    )


def _discard_future_checkpoints(directory: str, epoch: int) -> None:
    """Primary-only, called on resume: delete checkpoint artifacts newer than
    the epoch being resumed. They belong to an abandoned trajectory (a torn
    sharded dir from the crash, or single-file checkpoints the rerun will
    re-earn), and a stale sharded dir is actively dangerous: the retrained
    epoch would re-save into it, and a second crash could leave a complete-
    looking dir mixing shard files from two different trainings."""
    import shutil

    if not os.path.isdir(directory):
        return
    for name in os.listdir(directory):
        m = CHECKPOINT_RE.search(name)
        if not m or int(m.group(1)) <= epoch:
            continue
        full = os.path.join(directory, name)
        if os.path.isdir(full):
            shutil.rmtree(full, ignore_errors=True)
        else:
            os.remove(full)
            for suffix in (DIGEST_SUFFIX, META_SUFFIX):
                try:
                    os.remove(full + suffix)
                except OSError:
                    pass  # no sidecar (legacy file), or already gone


def _host_syncable(leaf) -> bool:
    """Whether a leaf's value can be host-gathered on any single process:
    non-arrays, process-local arrays, and fully-REPLICATED global arrays
    (device_get special-cases those even when they span hosts). Only arrays
    genuinely SHARDED across processes (model-parallel stage/TP/FSDP shards)
    are excluded — they cannot be gathered from one process and need no sync
    either: every process materialized them from the same deterministic SPMD
    init program."""
    return (
        not isinstance(leaf, jax.Array)
        or leaf.is_fully_replicated
        or leaf.is_fully_addressable
    )


def gather_to_host(tree: PyTree) -> PyTree:
    """Assemble every leaf's full GLOBAL value as host numpy arrays — the
    export-from-model-parallel-state bridge.

    Single-process-visible leaves (host arrays, process-local device
    arrays, fully-replicated global arrays, single-host TP/FSDP layouts)
    are a plain ``device_get``. Leaves sharded ACROSS processes
    (multi-host TP/FSDP/pipeline layouts) make this a **collective**:
    every process must call it. Each contributes the shard pieces it owns
    (``replica_id == 0`` — `save_sharded`'s dedup) over one fused
    host-level allgather, and every process reassembles the global arrays
    with the sharded-checkpoint piece-tiling machinery (`_assemble_global`)
    — the in-memory twin of a ``save_sharded → restore_sharded
    (reshard=True)`` roundtrip, no disk involved. Costs one host-RAM copy
    of the tree per process; a tree too large to assemble on one host
    cannot be exported as a single-device program — shard-and-serve is the
    workflow (`save_sharded` + a resharded restore on the serving fleet).
    """
    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = [l for _, l in paths_and_leaves]
    cross = {
        i for i, l in enumerate(leaves)
        if isinstance(l, jax.Array) and not _host_syncable(l)
    }
    if not cross:
        return jax.device_get(tree)
    payload = {}
    meta = {}
    for i in cross:
        leaf = leaves[i]
        meta[i] = (tuple(leaf.shape), np.dtype(leaf.dtype))
        for spec, piece in leaf_shard_pieces(leaf).items():
            payload[f"{i}|{spec}"] = piece
    store: dict = {}
    for part in collectives.allgather_object(payload):
        store.update(part)
    try:
        out = [
            _assemble_global(store, i, *meta[i]) if i in cross
            else jax.device_get(leaf)
            for i, leaf in enumerate(leaves)
        ]
    except MemoryError as e:
        raise MemoryError(
            "gather_to_host could not assemble the full model on this "
            "host — a model that large cannot be exported as a "
            "single-device serving program. Workflow: save_sharded(dir, "
            "state) from every training process, then restore_sharded("
            "dir, template, reshard=True) onto the serving fleet's own "
            "mesh."
        ) from e
    return jax.tree_util.tree_unflatten(treedef, out)


def broadcast_parameters(tree: PyTree, root_rank: int = 0, mesh=None) -> PyTree:
    """``hvd.broadcast_global_variables(0)`` equivalent for any pytree:
    every process adopts the root's values; with ``mesh`` given,
    host-syncable leaves are re-placed replicated on the mesh, and with
    ``mesh=None`` each leaf keeps its own sharding. Leaves sharded across
    processes are left untouched (see `_host_syncable`)."""
    if jax.process_count() > 1:
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        idx = [i for i, l in enumerate(leaves) if _host_syncable(l)]
        synced = collectives.broadcast_pytree(
            [jax.device_get(leaves[i]) for i in idx], root=root_rank
        )
        for i, host_val in zip(idx, synced):
            old = leaves[i]
            if isinstance(old, jax.Array) and mesh is None:
                leaves[i] = jax.device_put(host_val, old.sharding)
            else:
                leaves[i] = host_val
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
    if mesh is not None:
        tree = jax.tree.map(
            lambda l: jax.device_put(l, sharding.replicated(mesh))
            if _host_syncable(l)
            else l,
            tree,
        )
    return tree


def restore_latest_and_broadcast(directory: str, template: PyTree, mesh=None,
                                 *, reshard: bool = False,
                                 with_step: bool = False):
    """The full resume path (§5.3): the primary loads the newest checkpoint,
    all processes adopt it. Returns (state, epoch) — epoch 0 if none found.
    ``with_step=True`` returns (state, epoch, step) instead: the
    STEP-granular resume point from the checkpoint's progress manifest
    (`checkpoint_progress`), broadcast alongside the epoch so every rank
    resumes ``fit(initial_epoch=epoch, initial_step=step)`` identically —
    manifest-less checkpoints read as step 0, so callers need no legacy
    branch. Without ``with_step``, mid-epoch artifacts
    (``HVT_SAVE_EVERY_STEPS``) are SKIPPED in favor of the newest
    complete-epoch checkpoint (`latest_checkpoint(complete_only=True)`):
    a step-unaware caller resuming ``fit(initial_epoch=)`` from mid-epoch
    weights would re-apply the epoch prefix's data to weights that
    already trained it — mid-epoch checkpoints are consumable only by
    step-aware resume. ``reshard=True`` additionally accepts sharded
    checkpoints saved under a different topology/layout (see
    `restore_sharded`).

    Collective-safe under single-writer checkpoints: only the *primary's*
    view of the directory decides (checkpoints may exist on its filesystem
    only), and that decision is broadcast first so every process takes the
    same branch — no process can skip a collective the others entered."""
    primary = runtime.is_primary()
    path = (
        latest_checkpoint(directory, complete_only=not with_step)
        if primary else None
    )
    epoch = int(CHECKPOINT_RE.search(path).group(1)) if path else 0
    step = checkpoint_progress(path)[1] if path else 0
    # A directory holding ONLY torn sharded dirs (the signature of a
    # rank-gated ModelCheckpoint on a model-parallel run — rank 0 wrote its
    # shard every epoch, the other ranks never did) must NOT silently resume
    # from scratch discarding all progress. The torn flag travels in the
    # broadcast header so EVERY rank raises together — a primary-only raise
    # would leave the other ranks blocked in the broadcast collective below.
    torn = (
        _torn_sharded_dirs(directory) if primary and not path else []
    )
    if primary:
        # Kill abandoned-future artifacts before training overwrites them —
        # see _discard_future_checkpoints for why this is load-bearing for
        # the sharded format, not just hygiene.
        _discard_future_checkpoints(directory, epoch)
    # Sharded = directory (isdir on the primary's actual pick — names are
    # user-controlled); non-primaries need the real NAME, not a guess, so
    # it travels alongside the epoch. One KV-store object broadcast, NOT
    # the fixed-width-array device path: host-staged buffers through
    # broadcast_one_to_all are unreliable on the compat floor (see
    # collectives._kv_client — gloo returns nondeterministic garbage for
    # them, which here decoded into a corrupt checkpoint name on
    # model-parallel meshes).
    sharded = bool(path) and os.path.isdir(path)
    name = os.path.basename(path) if path else ""
    if jax.process_count() > 1:
        epoch, sharded, n_torn, step, name = collectives.broadcast_object(
            (epoch, sharded, len(torn), step, name), root=0
        )
    else:
        n_torn = len(torn)
    if n_torn:
        detail = (
            f" (e.g. {os.path.basename(torn[-1])})" if torn else ""
        )
        raise RuntimeError(
            f"no complete checkpoint in {directory}, but {n_torn} "
            f"incomplete sharded checkpoint(s) exist{detail}. Causes: "
            "(a) the saver was gated to one rank — for cross-process-"
            "sharded state EVERY process must run ModelCheckpoint/"
            "save_checkpoint; (b) a crash during the very first save; "
            "(c) every saved shard failed its sha256 digest check "
            "(corruption). Fix the gating (a) or delete the torn "
            "dir(s) to start fresh (b/c)."
        )
    def ret(state, epoch, step):
        return (state, epoch, step) if with_step else (state, epoch)

    if epoch == 0 and step == 0:
        # Nothing to resume. (A mid-epoch save DURING epoch 0 is
        # checkpoint-0 with step > 0 — real progress, restored below.)
        return ret(template, 0, 0)
    if sharded:
        # Collective restore: every process reads the shard bytes its own
        # template shardings need — identical bytes for replicated leaves,
        # so the post-restore broadcast is unnecessary by construction.
        spath = os.path.join(directory, name)
        return ret(
            restore_sharded(spath, template, reshard=reshard), epoch, step
        )
    state = restore(path, template) if primary else template
    return ret(broadcast_parameters(state, mesh=mesh), epoch, step)


# --- Serving export (TF-free SavedModel role) ------------------------------

SIGNATURE_FILE = "signature.json"
GRAPH_FILE = "model.stablehlo"
WEIGHTS_FILE = "weights.msgpack"


def export_serving(
    export_dir: str,
    apply_fn,
    params: PyTree,
    input_shape: tuple,
    input_dtype=np.float32,
    timestamp: str | None = None,
    format: str = "stablehlo",
) -> str:
    """Export a serving bundle into ``export_dir/<YYYYmmdd-HHMMSS>/``.

    ``apply_fn(params, x)`` must return logits; the exported program is the
    jitted ``x → softmax(logits)`` closure over the weights, with the
    reference's serving signature ``{'input' → 'prob'}``
    (mnist_keras.py:126-140). Primary-process-only by convention (caller
    script gates, like the reference's ``if hvd.rank() == 0``).

    Formats:
      * ``'stablehlo'`` (default) — portable StableHLO via `jax.export`
        plus msgpack weights and a JSON signature; reloadable by
        `load_serving` with no TF anywhere.
      * ``'savedmodel'`` — a TF SavedModel via ``jax2tf`` with a
        ``serving_default`` signature (``input`` → ``prob``, dynamic batch
        dim), loadable by any standard TF Serving stack — byte-for-role
        parity with the reference's SavedModelBuilder export. Requires
        TensorFlow importable.

    **Model-parallel state**: params sharded within one process (TP/FSDP
    on a single-host mesh) export transparently. Params sharded ACROSS
    processes (multi-host TP/FSDP, pipeline stages) make this a
    collective: EVERY process must call export_serving (drop the
    is_primary gate); the shards are host-gathered (`gather_to_host`),
    the primary writes the bundle, and non-primaries return None.
    """
    stamp = timestamp or time.strftime("%Y%m%d-%H%M%S")
    out_dir = os.path.join(export_dir, stamp)

    if is_cross_process_sharded(params):
        params = gather_to_host(params)  # collective — see docstring
        if not runtime.is_primary():
            return None
    else:
        # Single-process shardings (TP/FSDP on one host) assemble here.
        params = jax.device_get(params)
    # Re-materialize as (single-device) jax arrays: apply_fns that index
    # params directly (e.g. PipelinedLM's embed[tokens]) would otherwise
    # hit numpy's __getitem__ with a tracer.
    import jax.numpy as jnp

    params = jax.tree.map(jnp.asarray, params)

    def predict(x):
        return jax.nn.softmax(apply_fn(params, x), axis=-1)

    if format == "savedmodel":
        return _export_savedmodel(
            out_dir, predict, input_shape, input_dtype
        )
    if format != "stablehlo":
        raise ValueError(
            f"unknown export format {format!r}; expected 'stablehlo' or "
            "'savedmodel'"
        )
    from jax import export as jax_export

    os.makedirs(out_dir, exist_ok=True)
    spec = jax.ShapeDtypeStruct(input_shape, input_dtype)
    exported = jax_export.export(jax.jit(predict))(spec)
    # Atomic + digested like every other artifact: a preemption mid-export
    # must not leave a torn bundle that serve_forever then loads.
    _atomic_write(
        os.path.join(out_dir, GRAPH_FILE), exported.serialize(), digest=True
    )
    _atomic_write(
        os.path.join(out_dir, WEIGHTS_FILE), serialization.to_bytes(params),
        digest=True,
    )
    _atomic_write(
        os.path.join(out_dir, SIGNATURE_FILE),
        json.dumps(
            {
                "signature": {"inputs": {"input": {"shape": list(input_shape),
                                                   "dtype": np.dtype(input_dtype).name}},
                              "outputs": {"prob": {}}},
                "format": "stablehlo+msgpack",
                "created": stamp,
            },
            indent=2,
        ).encode(),
        digest=True,
    )
    return out_dir


def _export_savedmodel(out_dir, predict, input_shape, input_dtype) -> str:
    """TF SavedModel export (the reference's interop contract,
    mnist_keras.py:126-140): jax2tf-convert the predict closure, wrap the
    output under the ``prob`` key, and save with a ``serving_default``
    signature whose input tensor is named ``input``. The batch dim is
    polymorphic so a serving stack can batch freely."""
    import tensorflow as tf
    from jax.experimental import jax2tf

    converted = jax2tf.convert(
        predict,
        polymorphic_shapes=["(b, ...)"],
        with_gradient=False,
        # Embed lowerings for BOTH platforms: without this, an export made
        # from a TPU-backed trainer pins the StableHLO module to TPU and a
        # CPU TF-Serving stack refuses it with "platform CPU is not among
        # the platforms required" (caught driving the real-chip example).
        native_serialization_platforms=("cpu", "cuda", "tpu"),
    )
    tf_fn = tf.function(
        lambda x: {"prob": converted(x)},
        input_signature=[
            tf.TensorSpec(
                (None,) + tuple(input_shape[1:]),
                tf.dtypes.as_dtype(np.dtype(input_dtype)),
                name="input",
            )
        ],
        autograph=False,
    )
    module = tf.Module()
    module.predict = tf_fn
    tf.saved_model.save(
        module,
        out_dir,
        signatures={"serving_default": tf_fn.get_concrete_function()},
    )
    return out_dir


def load_serving(bundle_dir: str):
    """Reload an exported STABLEHLO bundle; returns ``fn(input) -> prob``.
    (SavedModel bundles are TF's to load: ``tf.saved_model.load``.)"""
    from jax import export as jax_export

    if os.path.exists(os.path.join(bundle_dir, "saved_model.pb")):
        raise ValueError(
            f"{bundle_dir} is a TF SavedModel export "
            "(format='savedmodel'); load it with tf.saved_model.load, "
            "not checkpoint.load_serving"
        )
    with open(os.path.join(bundle_dir, GRAPH_FILE), "rb") as f:
        exported = jax_export.deserialize(f.read())
    # jit the deserialized program once: a bare exported.call re-lowers on
    # every invocation (measured seconds per request at LM scale; the same
    # finding behind serving.GenerateBundle._call).
    return jax.jit(exported.call)
