"""Dataset providers with the reference's loading contract, egress-free.

Parity target: ``tf.keras.datasets.mnist.load_data(path='mnist-%d.npz' % rank)``
(tensorflow2_keras_mnist.py:34-35) and ``mnist.load_data()``
(mnist_keras.py:48): return ``(x_train, y_train), (x_test, y_test)`` as uint8
images / int labels, cached in an ``.npz`` file whose per-rank name avoids
concurrent-download filesystem races (SURVEY.md §5.2).

This environment has no network egress, so when no real dataset archive is
present on disk we *synthesize* a deterministic, learnable stand-in with the
exact same shapes/dtypes/split sizes:

* ``mnist``   — 60k/10k 28×28×1 uint8: digit glyphs (5×7 bitmap font,
  3× upscaled) placed at random offsets with intensity jitter and Gaussian
  noise. A small CNN reaches >98% test accuracy, so the reference's
  convergence gates (loss ∈ [0, 0.3], 98%-val-acc north star) stay
  meaningful.
* ``cifar10`` — 50k/10k 32×32×3 uint8: class-conditional colored frequency
  textures + noise (for the ResNet-20 heavier-gradient benchmark config,
  BASELINE.json config 4).

If a genuine ``mnist.npz``/``cifar10.npz`` (keras layout) exists at the cache
path, it is loaded instead — the synthetic path is a fallback, not a fork of
the API.
"""

from __future__ import annotations

import os

import numpy as np

from horovod_tpu.analysis import registry
from horovod_tpu.data import stream as stream_lib

# 5x7 bitmap font for digits 0-9 (rows top→bottom, 5 bits per row).
_DIGIT_FONT = {
    0: ["01110", "10001", "10011", "10101", "11001", "10001", "01110"],
    1: ["00100", "01100", "00100", "00100", "00100", "00100", "01110"],
    2: ["01110", "10001", "00001", "00010", "00100", "01000", "11111"],
    3: ["11111", "00010", "00100", "00010", "00001", "10001", "01110"],
    4: ["00010", "00110", "01010", "10010", "11111", "00010", "00010"],
    5: ["11111", "10000", "11110", "00001", "00001", "10001", "01110"],
    6: ["00110", "01000", "10000", "11110", "10001", "10001", "01110"],
    7: ["11111", "00001", "00010", "00100", "01000", "01000", "01000"],
    8: ["01110", "10001", "10001", "01110", "10001", "10001", "01110"],
    9: ["01110", "10001", "10001", "01111", "00001", "00010", "01100"],
}


def _glyphs() -> np.ndarray:
    """(10, 21, 15) float glyph bank: 5x7 font, 3x nearest-neighbor upscale."""
    bank = np.zeros((10, 21, 15), np.float32)
    for d, rows in _DIGIT_FONT.items():
        bitmap = np.array([[int(c) for c in row] for row in rows], np.float32)
        bank[d] = np.kron(bitmap, np.ones((3, 3), np.float32))
    return bank


def _synth_mnist_split(n: int, seed: int):
    """Deterministic synthetic MNIST-shaped split: (n,28,28) uint8 + (n,) int64."""
    rng = np.random.RandomState(seed)
    glyphs = _glyphs()  # (10, 21, 15)
    labels = rng.randint(0, 10, size=n).astype(np.int64)
    # Random placement of the 21x15 glyph inside the 28x28 canvas.
    oy = rng.randint(0, 28 - 21 + 1, size=n)
    ox = rng.randint(0, 28 - 15 + 1, size=n)
    intensity = rng.uniform(0.65, 1.0, size=n).astype(np.float32)
    images = rng.normal(0.0, 0.06, size=(n, 28, 28)).astype(np.float32)
    # Vectorized scatter via advanced indexing on a per-sample window.
    gy, gx = np.meshgrid(np.arange(21), np.arange(15), indexing="ij")
    rows = oy[:, None, None] + gy[None]  # (n, 21, 15)
    cols = ox[:, None, None] + gx[None]
    samp = np.arange(n)[:, None, None]
    images[samp, rows, cols] += glyphs[labels] * intensity[:, None, None]
    np.clip(images, 0.0, 1.0, out=images)
    return (images * 255).astype(np.uint8), labels


def _load_or_create(path: str, cache_dir: str | None, synthesize):
    """Shared cache contract: read the keras-layout npz if present, else
    materialize via ``synthesize() -> ((xtr, ytr), (xte, yte))`` with an
    atomic rename (no torn files under concurrent writers)."""
    cache_dir = cache_dir or os.path.expanduser(
        registry.get_str("HVT_DATA_DIR")
    )
    full = path if os.path.isabs(path) else os.path.join(cache_dir, path)
    if os.path.exists(full):
        def read_npz():
            with np.load(full) as f:
                return (
                    (f["x_train"], f["y_train"]),
                    (f["x_test"], f["y_test"]),
                )

        return stream_lib.read_with_retries(read_npz, full)
    (x_train, y_train), (x_test, y_test) = synthesize()
    os.makedirs(os.path.dirname(full), exist_ok=True)
    tmp = f"{full}.tmp.{os.getpid()}.npz"  # keep .npz: savez appends it otherwise
    np.savez_compressed(
        tmp, x_train=x_train, y_train=y_train, x_test=x_test, y_test=y_test
    )
    os.replace(tmp, full)
    return (x_train, y_train), (x_test, y_test)


def mnist(path: str = "mnist.npz", cache_dir: str | None = None):
    """Return ``(x_train, y_train), (x_test, y_test)`` — keras-layout MNIST.

    ``path`` mirrors the reference's per-rank cache filename convention
    (``'mnist-%d.npz' % hvd.rank()``, tensorflow2_keras_mnist.py:35): the
    first call materializes the npz, later calls read it back; distinct
    per-rank paths keep co-located processes from racing on one file.
    """
    return _load_or_create(
        path,
        cache_dir,
        lambda: (_synth_mnist_split(60_000, seed=0), _synth_mnist_split(10_000, seed=1)),
    )


def _synth_cifar_split(n: int, seed: int):
    """Class-conditional colored textures: (n,32,32,3) uint8 + (n,) int64."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int64)
    yy, xx = np.meshgrid(np.arange(32), np.arange(32), indexing="ij")
    # Per-class signature: orientation + frequency + RGB phase offsets.
    freqs = 1 + (np.arange(10) % 5)
    angles = (np.arange(10) * 36) * np.pi / 180.0
    phase = rng.uniform(0, 2 * np.pi, size=(n, 3)).astype(np.float32)
    proj = (
        np.cos(angles)[labels][:, None, None] * xx[None]
        + np.sin(angles)[labels][:, None, None] * yy[None]
    )  # (n, 32, 32)
    base = np.sin(
        proj[..., None] * (freqs[labels][:, None, None, None] * 2 * np.pi / 32)
        + phase[:, None, None, :]
    )  # (n, 32, 32, 3)
    images = 0.5 + 0.35 * base + rng.normal(0, 0.08, size=base.shape)
    np.clip(images, 0.0, 1.0, out=images)
    return (images * 255).astype(np.uint8), labels


def cifar10(path: str = "cifar10.npz", cache_dir: str | None = None):
    """CIFAR-10-shaped splits: 50k/10k 32×32×3 uint8 (same contract as mnist())."""
    return _load_or_create(
        path,
        cache_dir,
        lambda: (_synth_cifar_split(50_000, seed=0), _synth_cifar_split(10_000, seed=1)),
    )


def copy_task(
    n_sequences: int, seq_len: int, vocab_size: int = 64, seed: int = 0
):
    """Long-range-recall LM dataset: the second half of each sequence repeats
    the first half, so predicting token ``t ≥ T/2`` requires attending ``T/2``
    positions back — a direct functional test of sequence-parallel attention
    (a model whose ring/Ulysses attention were broken could still fit local
    statistics, but could never drive recall-half loss to ~0).

    Returns ``(inputs, labels)`` int32 arrays of shape
    ``[n_sequences, seq_len]`` (next-token pairs over a BOS-prefixed
    sequence, so the length stays divisible by any seq mesh axis). Token 0
    is the BOS and never sampled; label positions ``seq_len//2 ..`` are the
    recall half."""
    if seq_len % 2 != 0:
        raise ValueError("seq_len must be even")
    rng = np.random.RandomState(seed)
    half = seq_len // 2
    first = rng.randint(1, vocab_size, size=(n_sequences, half))
    bos = np.zeros((n_sequences, 1), dtype=first.dtype)
    tokens = np.concatenate([bos, first, first], axis=1).astype(np.int32)
    return tokens[:, :-1], tokens[:, 1:]
