"""ctypes binding for the native batch-assembly engine (native/hvt_data.cc).

The framework's native-runtime component (SURVEY.md §2.3: the reference's
C++ layer is Horovod's core; the collective half of that role is owned by
XLA here, the host-IO half is this): a C++ producer thread permutes,
gathers and stages training batches into a ring of reusable buffers while
the accelerator runs the previous step.

`NativeBatchLoader` is a drop-in for the training-path `ArrayDataset`
pipeline (full reshuffle each epoch, repeat-forever, drop-remainder — the
same semantics `Trainer.fit(x=, y=)` builds). `available()` reports whether
the shared library could be loaded/built; callers fall back to the Python
pipeline when it can't, so the framework works without a toolchain.

By default each yielded array is an owned copy (safe under any lifetime —
JAX's async device_put may read host buffers after dispatch, and a GC'd
loader frees its slots). The shuffle/gather still happens off-thread; the
one extra memcpy per batch is noise. ``copy=False`` yields zero-copy views
valid only until the next ``__next__`` call and only while the loader
object is alive — for callers that consume synchronously.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Sequence

import numpy as np

from horovod_tpu.analysis import registry

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhvt_data.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def _load():
    """Load (building on first use) the shared library; None on failure."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if registry.get_flag("HVT_NO_NATIVE"):
            _load_failed = True
            return None
        # Always run make (a no-op when up to date) so the Makefile's source
        # dependency governs rebuilds — a stale .so never shadows an edited
        # hvt_data.cc.
        try:
            subprocess.run(
                ["make", "-s", "libhvt_data.so"],
                cwd=_NATIVE_DIR,
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception:
            if not os.path.exists(_LIB_PATH):
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _load_failed = True
            return None
        # ABI handshake: a stale prebuilt .so (no compiler to rebuild,
        # make failed above) predating the epoch-anchored stream would
        # silently IGNORE the extra create arguments — the cursors would
        # then describe a stream nobody produces. Missing symbol or
        # version mismatch → treat the native engine as unavailable and
        # fall back to the python pipeline (fail-safe, never
        # fail-different-bytes).
        try:
            if lib.hvt_loader_abi_version() != 2:
                _load_failed = True
                return None
        except AttributeError:
            _load_failed = True
            return None
        lib.hvt_loader_create.restype = ctypes.c_void_p
        lib.hvt_loader_create.argtypes = [
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.hvt_loader_next.restype = ctypes.c_int
        lib.hvt_loader_next.argtypes = [ctypes.c_void_p]
        lib.hvt_loader_slot_ptr.restype = ctypes.c_void_p
        lib.hvt_loader_slot_ptr.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int]
        lib.hvt_loader_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.hvt_loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeBatchLoader:
    """Infinite iterator of ``(arr_0[batch], arr_1[batch], ...)`` tuples
    assembled off-thread in C++. Fresh full permutation per epoch
    (``shuffle=True``), batches never straddle the epoch remainder."""

    def __init__(
        self,
        arrays: Sequence[np.ndarray],
        batch_size: int,
        seed: int = 0,
        shuffle: bool = True,
        n_slots: int = 4,
        copy: bool = True,
        start_epoch: int = 0,
        batches_per_epoch: int = 0,
    ):
        """``start_epoch``/``batches_per_epoch`` anchor the stream's
        epochs (the durable-cursor contract — see `data.stream` and the
        hvt_data.cc header): every pass's permutation is a pure function
        of ``(seed, epoch, pass)``, so the stream can start at ANY
        absolute epoch without replaying the ones before it.
        ``batches_per_epoch=0`` keeps one-permutation-pass-per-epoch
        semantics; > 0 cuts epochs at exactly that many batches."""
        self.copy = copy
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native loader unavailable (build native/libhvt_data.so)"
            )
        self._lib = lib
        # Keep C-contiguous copies alive for the library's lifetime — it
        # borrows these base pointers.
        self._arrays = [np.ascontiguousarray(a) for a in arrays]
        n = self._arrays[0].shape[0]
        if any(a.shape[0] != n for a in self._arrays):
            raise ValueError("all arrays must share the leading dimension")
        if batch_size > n:
            raise ValueError(f"batch_size {batch_size} > dataset size {n}")
        self.batch_size = int(batch_size)
        self._shapes = [(self.batch_size,) + a.shape[1:] for a in self._arrays]
        self._dtypes = [a.dtype for a in self._arrays]

        ptrs = (ctypes.c_void_p * len(self._arrays))(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self._arrays]
        )
        row_bytes = (ctypes.c_int64 * len(self._arrays))(
            *[a.strides[0] for a in self._arrays]
        )
        self._handle = lib.hvt_loader_create(
            ptrs, row_bytes, len(self._arrays), n, self.batch_size,
            n_slots, seed, 1 if shuffle else 0,
            int(start_epoch), int(batches_per_epoch),
        )
        if not self._handle:
            raise RuntimeError("hvt_loader_create failed")
        self._held_slot = -1
        # Cursor bookkeeping (mirrors the producer's position exactly:
        # both sides count consumed batches of the same deterministic
        # stream). Epoch length in batches: the explicit cut when given,
        # else the pass length (drop-remainder permutation batches).
        self._seed = int(seed)
        self._shuffle = bool(shuffle)
        self._batches_per_epoch = (
            int(batches_per_epoch) or n // self.batch_size
        )
        self._epoch = int(start_epoch)
        self._batch_in_epoch = 0

    def _advance(self, n_batches: int = 1) -> None:
        self._batch_in_epoch += n_batches
        while self._batch_in_epoch >= self._batches_per_epoch:
            self._batch_in_epoch -= self._batches_per_epoch
            self._epoch += 1

    def __iter__(self):
        return self

    def __next__(self):
        if self._handle is None:
            raise StopIteration
        if self._held_slot >= 0:
            # Previous batch's buffers are recycled now (documented lifetime).
            self._lib.hvt_loader_release(self._handle, self._held_slot)
            self._held_slot = -1
        slot = self._lib.hvt_loader_next(self._handle)
        if slot < 0:
            raise StopIteration
        self._held_slot = slot
        self._advance()
        out = []
        for idx, (shape, dtype) in enumerate(zip(self._shapes, self._dtypes)):
            ptr = self._lib.hvt_loader_slot_ptr(self._handle, slot, idx)
            size = int(np.prod(shape)) * dtype.itemsize
            buf = (ctypes.c_char * size).from_address(ptr)
            arr = np.frombuffer(buf, dtype=dtype).reshape(shape)
            out.append(arr.copy() if self.copy else arr)
        return tuple(out)

    def skip(self, n_batches: int) -> None:
        """Fast-forward the stream past ``n_batches`` batches without a
        host copy: each skipped slot is advanced and released unread (the
        C++ producer's ring recycles it), so the loader's permutation
        stream lands exactly where an uninterrupted consumer would be —
        the step-granular resume hook (`Trainer.fit(initial_step=)`)."""
        if self._handle is None:
            raise RuntimeError("loader is closed")
        if self._held_slot >= 0:
            self._lib.hvt_loader_release(self._handle, self._held_slot)
            self._held_slot = -1
        for _ in range(int(n_batches)):
            slot = self._lib.hvt_loader_next(self._handle)
            if slot < 0:
                raise RuntimeError("native loader stream ended during skip")
            self._lib.hvt_loader_release(self._handle, slot)
            self._advance()

    def cursor(self):
        """The position of the NEXT batch this loader will yield, as a
        serializable `data.stream.StreamCursor`. Reconstruct with
        `NativeBatchLoader.from_cursor(arrays, cursor)` — byte-identical
        continuation of the same (seed, epoch, pass)-anchored stream."""
        from horovod_tpu.data import stream as stream_lib

        return stream_lib.StreamCursor(
            kind="native", seed=self._seed, epoch=self._epoch,
            step=self._batch_in_epoch,
            position={
                "n_examples": self._arrays[0].shape[0],
                "batch_size": self.batch_size,
                "shuffle": self._shuffle,
                "batches_per_epoch": self._batches_per_epoch,
            },
        )

    @classmethod
    def from_cursor(cls, arrays: Sequence[np.ndarray], cursor, **kw):
        """Rebuild a loader positioned exactly at ``cursor`` (validated
        loudly — format, kind, seed, geometry; `stream.StreamCursorError`
        on any mismatch). The within-epoch offset is skipped natively
        (slots advanced and released, no host copy)."""
        from horovod_tpu.data import stream as stream_lib

        if not isinstance(cursor, stream_lib.StreamCursor):
            cursor = stream_lib.StreamCursor.from_dict(cursor)
        n = int(np.asarray(arrays[0]).shape[0])
        cursor.require("native", n_examples=n)
        try:
            batch_size = int(cursor.position["batch_size"])
            if batch_size < 1:
                raise ValueError(batch_size)
        except (KeyError, TypeError, ValueError):
            raise stream_lib.StreamCursorError(
                "native cursor carries no usable batch_size — refusing "
                "to guess the stream geometry"
            ) from None
        bpe = int(cursor.position.get("batches_per_epoch") or 0)
        loader = cls(
            arrays, batch_size, seed=cursor.seed,
            shuffle=bool(cursor.position.get("shuffle", True)),
            start_epoch=cursor.epoch,
            batches_per_epoch=(
                0 if bpe == n // batch_size else bpe
            ),
            **kw,
        )
        if cursor.step:
            loader.skip(cursor.step)
        return loader

    def close(self):
        if self._handle is not None:
            self._lib.hvt_loader_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
