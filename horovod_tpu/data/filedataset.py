"""File-backed sharded datasets — feeding sets bigger than host RAM.

The reference's data story is `mnist.load_data()` into memory
(tensorflow2_keras_mnist.py:34-41); at framework scale the dataset lives
on disk in shards and the host touches only the rows of the current
batch. This module is that path with zero dependencies:

* `write_shards(columns, dir)` — named columns ({'x': ..., 'y': ...}) cut
  into ``shard_size``-row pieces, one ``.npy`` per column per shard plus
  an ``index.json`` (atomic). `.npy` (not `.npz`) because numpy can
  MEMORY-MAP it: readers never load a shard, they map it.
* `FileDataset(dir)` — lazily mmaps shards on first touch; batch assembly
  gathers exactly the requested rows through the maps (the OS page cache
  is the working set, not a Python copy of the dataset).
* `.batches(...)` — per-epoch global permutation (seeded), optional
  repeat, and per-process striping (``shard=(index, count)``), mirroring
  `ArrayDataset.shard`'s every-count-th-row split. `.pairs('x', 'y', ...)`
  yields the ``(x, y)`` tuples `Trainer.fit(dataset=...)`` consumes.

This is the host-side cold path; the hot path stays the same — batches
land on device through `sharding.shard_batch` exactly like in-memory
feeding.
"""

from __future__ import annotations

import json
import os

import numpy as np

INDEX_FILE = "index.json"
_FORMAT = "hvt-shards-v1"


def write_shards(columns: dict, directory: str, shard_size: int = 8192) -> str:
    """Cut named columns into on-disk shards. Returns ``directory``."""
    if not isinstance(columns, dict) or not columns:
        raise ValueError("columns must be a non-empty dict of name -> array")
    arrays = {k: np.asarray(v) for k, v in columns.items()}
    n = len(next(iter(arrays.values())))
    if any(len(a) != n for a in arrays.values()):
        raise ValueError("all columns must share the leading dimension")
    if shard_size < 1:
        raise ValueError(f"shard_size must be >= 1, got {shard_size}")
    os.makedirs(directory, exist_ok=True)
    if os.path.exists(os.path.join(directory, INDEX_FILE)):
        # Rewriting in place cannot be made crash-atomic (shards would be
        # overwritten before the new index lands, and a live reader's mmap
        # can SIGBUS under truncation) — refuse; write a fresh directory.
        raise ValueError(
            f"{directory} already holds a dataset (index.json present); "
            "write_shards only creates fresh directories"
        )
    n_shards = -(-n // shard_size)
    for s in range(n_shards):
        lo, hi = s * shard_size, min((s + 1) * shard_size, n)
        for key, arr in arrays.items():
            np.save(os.path.join(directory, f"shard-{s:05d}.{key}.npy"),
                    arr[lo:hi])
    index = {
        "format": _FORMAT,
        "n_examples": n,
        "shard_size": shard_size,
        "n_shards": n_shards,
        "columns": {
            # dtype.str, not dtype.name: .name does not round-trip for
            # string/bytes columns ('<U2' -> 'str160', which np.dtype
            # rejects on read).
            k: {"dtype": a.dtype.str, "shape": list(a.shape[1:])}
            for k, a in arrays.items()
        },
    }
    # Atomic: a reader never sees a directory with an index but missing
    # shards (the index is written LAST) or a torn index.
    from horovod_tpu.checkpoint import _atomic_write

    _atomic_write(
        os.path.join(directory, INDEX_FILE), json.dumps(index).encode()
    )
    return directory


class FileDataset:
    """Reader over a `write_shards` directory; shards memory-map lazily."""

    def __init__(self, directory: str):
        self.directory = directory
        with open(os.path.join(directory, INDEX_FILE)) as f:
            self.index = json.load(f)
        if self.index.get("format") != _FORMAT:
            raise ValueError(f"not a shard directory: {directory}")
        self.columns = tuple(self.index["columns"])
        self._maps: dict[tuple[int, str], np.ndarray] = {}

    @property
    def num_examples(self) -> int:
        return int(self.index["n_examples"])

    def _map(self, shard: int, key: str) -> np.ndarray:
        m = self._maps.get((shard, key))
        if m is None:
            m = np.load(
                os.path.join(self.directory, f"shard-{shard:05d}.{key}.npy"),
                mmap_mode="r",
            )
            self._maps[(shard, key)] = m
        return m

    def gather(self, rows: np.ndarray) -> dict:
        """Assemble the given global row ids (in order) as one dict batch —
        reads touch only those rows of the mapped shards."""
        rows = np.asarray(rows)
        size = int(self.index["shard_size"])
        shard_of, offset = rows // size, rows % size
        out = {
            k: np.empty(
                (len(rows),) + tuple(self.index["columns"][k]["shape"]),
                dtype=self.index["columns"][k]["dtype"],
            )
            for k in self.columns
        }
        for s in np.unique(shard_of):
            sel = shard_of == s
            offs = offset[sel]
            for k in self.columns:
                out[k][sel] = self._map(int(s), k)[offs]
        return out

    def batches(self, batch_size: int, *, seed: int = 0,
                shuffle: bool = True, repeat: bool = False,
                shard: tuple[int, int] = (0, 1),
                drop_remainder: bool = True):
        """Dict batches over a per-epoch seeded permutation.

        ``shard=(i, n)`` keeps every n-th example starting at i — the
        per-process split (`ArrayDataset.shard` semantics: disjoint,
        exhaustive)."""
        idx, cnt = shard
        if not (0 <= idx < cnt):
            raise ValueError(f"shard index {idx} out of range for {cnt}")
        mine = np.arange(self.num_examples)[idx::cnt]
        if drop_remainder and len(mine) < batch_size:
            # Every epoch would yield ZERO batches; with repeat=True the
            # loop would spin forever producing nothing — refuse loudly.
            raise ValueError(
                f"per-process stripe has {len(mine)} examples < batch_size "
                f"({batch_size}); shrink the batch or set "
                "drop_remainder=False"
            )
        rng = np.random.RandomState(seed)
        while True:
            order = rng.permutation(mine) if shuffle else mine
            for lo in range(0, len(order), batch_size):
                sel = order[lo : lo + batch_size]
                if len(sel) < batch_size and drop_remainder:
                    break
                yield self.gather(sel)
            if not repeat:
                return

    def pairs(self, x_key: str, y_key: str, batch_size: int, **kw):
        """(x, y) tuple batches for ``Trainer.fit(dataset=...)``."""
        for b in self.batches(batch_size, **kw):
            yield b[x_key], b[y_key]
