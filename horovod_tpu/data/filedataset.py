"""File-backed sharded datasets — feeding sets bigger than host RAM.

The reference's data story is `mnist.load_data()` into memory
(tensorflow2_keras_mnist.py:34-41); at framework scale the dataset lives
on disk in shards and the host touches only the rows of the current
batch. This module is that path with zero dependencies:

* `write_shards(columns, dir)` — named columns ({'x': ..., 'y': ...}) cut
  into ``shard_size``-row pieces, one ``.npy`` per column per shard plus
  an ``index.json`` (atomic). `.npy` (not `.npz`) because numpy can
  MEMORY-MAP it: readers never load a shard, they map it.
* `FileDataset(dir)` — lazily mmaps shards on first touch; batch assembly
  gathers exactly the requested rows through the maps (the OS page cache
  is the working set, not a Python copy of the dataset).
* `.batches(...)` — per-epoch global permutation (seeded), optional
  repeat, and per-process striping (``shard=(index, count)`` or the
  `.shard(i, n)`/`.reshard(i, n)` view chain, mirroring
  `ArrayDataset.shard`'s every-count-th-row split). `.pairs('x', 'y',
  ...)` yields the ``(x, y)`` tuples `Trainer.fit(dataset=...)` consumes;
  `.pairs_stream(...)` wraps them in a resumable view with the
  `batches(skip=, start_epoch=, batches_per_epoch=)` hook fit's
  fast-forward drives.

Durable stream cursors (`data.stream`): every epoch's permutation is a
PURE function of ``(seed, epoch, pass)`` (`stream.epoch_seed`), so any
position of the infinite stream — including epochs consumed by a process
that no longer exists — is reconstructible from a serializable
`StreamCursor` (`stream_cursor`/`batches_from`), byte-exactly.

Transient-I/O hardening: shard mmap opens go through
`stream.read_with_retries` — bounded retry-with-backoff
(``HVT_DATA_RETRIES`` × ``HVT_DATA_BACKOFF_S``) for the flaky-NFS class,
then a fast, actionable failure pointing at the checkpoint-restart path.

This is the host-side cold path; the hot path stays the same — batches
land on device through `sharding.shard_batch` exactly like in-memory
feeding.
"""

from __future__ import annotations

import json
import os

import numpy as np

from horovod_tpu.data import stream as stream_lib

INDEX_FILE = "index.json"
_FORMAT = "hvt-shards-v1"


def write_shards(columns: dict, directory: str, shard_size: int = 8192) -> str:
    """Cut named columns into on-disk shards. Returns ``directory``."""
    if not isinstance(columns, dict) or not columns:
        raise ValueError("columns must be a non-empty dict of name -> array")
    arrays = {k: np.asarray(v) for k, v in columns.items()}
    n = len(next(iter(arrays.values())))
    if any(len(a) != n for a in arrays.values()):
        raise ValueError("all columns must share the leading dimension")
    if shard_size < 1:
        raise ValueError(f"shard_size must be >= 1, got {shard_size}")
    os.makedirs(directory, exist_ok=True)
    if os.path.exists(os.path.join(directory, INDEX_FILE)):
        # Rewriting in place cannot be made crash-atomic (shards would be
        # overwritten before the new index lands, and a live reader's mmap
        # can SIGBUS under truncation) — refuse; write a fresh directory.
        raise ValueError(
            f"{directory} already holds a dataset (index.json present); "
            "write_shards only creates fresh directories"
        )
    n_shards = -(-n // shard_size)
    for s in range(n_shards):
        lo, hi = s * shard_size, min((s + 1) * shard_size, n)
        for key, arr in arrays.items():
            np.save(os.path.join(directory, f"shard-{s:05d}.{key}.npy"),
                    arr[lo:hi])
    index = {
        "format": _FORMAT,
        "n_examples": n,
        "shard_size": shard_size,
        "n_shards": n_shards,
        "columns": {
            # dtype.str, not dtype.name: .name does not round-trip for
            # string/bytes columns ('<U2' -> 'str160', which np.dtype
            # rejects on read).
            k: {"dtype": a.dtype.str, "shape": list(a.shape[1:])}
            for k, a in arrays.items()
        },
    }
    # Atomic: a reader never sees a directory with an index but missing
    # shards (the index is written LAST) or a torn index.
    from horovod_tpu.checkpoint import _atomic_write

    _atomic_write(
        os.path.join(directory, INDEX_FILE), json.dumps(index).encode()
    )
    return directory


class FileDataset:
    """Reader over a `write_shards` directory; shards memory-map lazily."""

    def __init__(self, directory: str):
        self.directory = directory

        def read_index():
            with open(os.path.join(directory, INDEX_FILE)) as f:
                return json.load(f)

        self.index = stream_lib.read_with_retries(
            read_index, f"{directory}/{INDEX_FILE}"
        )
        if self.index.get("format") != _FORMAT:
            raise ValueError(f"not a shard directory: {directory}")
        self.columns = tuple(self.index["columns"])
        self._maps: dict[tuple[int, str], np.ndarray] = {}
        # Per-process striping view state (ArrayDataset.shard parity):
        # the full row space is always on disk, so the view is just the
        # remembered (index, count) — `reshard` recuts from the full set.
        self._shard_spec: tuple[int, int] | None = None

    @property
    def num_examples(self) -> int:
        return int(self.index["n_examples"])

    # --- per-process striping views (ArrayDataset.shard parity) -------------

    def _view(self, spec: tuple[int, int] | None) -> "FileDataset":
        ds = object.__new__(FileDataset)
        ds.directory = self.directory
        ds.index = self.index
        ds.columns = self.columns
        ds._maps = self._maps  # shared: same files, same page cache
        ds._shard_spec = spec
        return ds

    def shard(self, index: int, count: int) -> "FileDataset":
        """A view keeping every count-th example starting at ``index`` —
        the per-process split (`ArrayDataset.shard` semantics: disjoint,
        exhaustive). The underlying directory always holds the FULL row
        space, so the view is cheap and `reshard` can recut it."""
        if not (0 <= index < count):
            raise ValueError(f"shard index {index} out of range for {count}")
        return self._view((int(index), int(count)))

    @property
    def shard_spec(self) -> tuple[int, int] | None:
        """(index, count) of this view's split; None if unsharded."""
        return self._shard_spec

    def reshard(self, index: int, count: int) -> "FileDataset":
        """Recut the per-process split at a NEW world size from the FULL
        row space — the elastic rescale hook, `ArrayDataset.reshard`
        parity for the file-backed path. Unlike chaining ``.shard()`` on
        an already-sharded ArrayDataset view (shards of shards), a
        FileDataset view always derives from the full on-disk set, so
        resharding is simply a fresh cut: across the new world the
        stripes again partition every example exactly once per epoch."""
        return self._view(None).shard(index, count)

    # --- row access ---------------------------------------------------------

    def _map(self, shard: int, key: str) -> np.ndarray:
        m = self._maps.get((shard, key))
        if m is None:
            path = os.path.join(
                self.directory, f"shard-{shard:05d}.{key}.npy"
            )
            # Bounded retry on the transient-I/O class (NFS blips, a
            # remounting FUSE volume); exhausted budget fails fast with
            # the checkpoint-fallback escalation (stream.read_with_retries).
            m = stream_lib.read_with_retries(
                lambda: np.load(path, mmap_mode="r"), path
            )
            self._maps[(shard, key)] = m
        return m

    def gather(self, rows: np.ndarray) -> dict:
        """Assemble the given global row ids (in order) as one dict batch —
        reads touch only those rows of the mapped shards."""
        rows = np.asarray(rows)
        size = int(self.index["shard_size"])
        shard_of, offset = rows // size, rows % size
        out = {
            k: np.empty(
                (len(rows),) + tuple(self.index["columns"][k]["shape"]),
                dtype=self.index["columns"][k]["dtype"],
            )
            for k in self.columns
        }
        for s in np.unique(shard_of):
            sel = shard_of == s
            offs = offset[sel]
            for k in self.columns:
                out[k][sel] = self._map(int(s), k)[offs]
        return out

    # --- iteration ----------------------------------------------------------

    def _stripe(self, shard: tuple[int, int] | None) -> np.ndarray:
        idx, cnt = shard if shard is not None else (
            self._shard_spec or (0, 1)
        )
        if not (0 <= idx < cnt):
            raise ValueError(f"shard index {idx} out of range for {cnt}")
        return np.arange(self.num_examples)[idx::cnt]

    def batches(self, batch_size: int, *, seed: int = 0,
                shuffle: bool = True, repeat: bool = False,
                shard: tuple[int, int] | None = None,
                drop_remainder: bool = True,
                skip: int = 0, start_epoch: int = 0,
                batches_per_epoch: int | None = None):
        """Dict batches over per-epoch seeded permutations.

        ``shard=(i, n)`` keeps every n-th example starting at i (defaults
        to this view's `.shard()` spec). Every epoch's permutation is a
        pure function of ``(seed, epoch, pass)``, so positions are
        addressable: ``batches(start_epoch=E, skip=S)`` continues the
        stream byte-exactly from S batches into epoch E — the durable
        cursor contract — and the skipped stretch gathers NOTHING (index
        arithmetic only).

        ``batches_per_epoch=None``: one permutation pass per epoch
        (``n_stripe // batch_size`` batches with ``drop_remainder``, the
        historical contract; ``repeat`` chains epochs).
        ``batches_per_epoch=B``: trainer-anchored epochs of exactly B
        batches (passes roll within the epoch when B exceeds one pass;
        partial batches never straddle passes — per-pass drop-remainder —
        and the stream is infinite regardless of ``repeat``)."""
        mine = self._stripe(shard)
        if drop_remainder and len(mine) < batch_size:
            # Every epoch would yield ZERO batches; with repeat=True the
            # loop would spin forever producing nothing — refuse loudly.
            raise ValueError(
                f"per-process stripe has {len(mine)} examples < batch_size "
                f"({batch_size}); shrink the batch or set "
                "drop_remainder=False"
            )

        def pass_order(epoch: int, pass_: int) -> np.ndarray:
            if not shuffle:
                return mine
            rng = np.random.RandomState(
                stream_lib.epoch_seed(seed, epoch, pass_)
            )
            return rng.permutation(mine)

        skip = int(skip)
        skipped = 0
        epoch = int(start_epoch)
        if batches_per_epoch is None:
            while True:
                order = pass_order(epoch, 0)
                for lo in range(0, len(order), batch_size):
                    sel = order[lo: lo + batch_size]
                    if len(sel) < batch_size and drop_remainder:
                        break
                    if skipped < skip:
                        skipped += 1
                        continue
                    yield self.gather(sel)
                epoch += 1
                if not repeat:
                    return
        B = int(batches_per_epoch)
        if B < 1:
            raise ValueError(f"batches_per_epoch must be >= 1, got {B}")
        per_pass = len(mine) // batch_size
        if per_pass < 1:
            raise ValueError(
                "batches_per_epoch requires at least one full batch per "
                "pass (drop-remainder anchoring)"
            )
        while True:
            emitted = 0
            pass_ = 0
            while emitted < B:
                order = pass_order(epoch, pass_)
                take = min(B - emitted, per_pass)
                for b in range(take):
                    if skipped < skip:
                        skipped += 1
                    else:
                        yield self.gather(
                            order[b * batch_size: (b + 1) * batch_size]
                        )
                emitted += take
                pass_ += 1
            epoch += 1

    def pairs(self, x_key: str, y_key: str, batch_size: int, **kw):
        """(x, y) tuple batches for ``Trainer.fit(dataset=...)``."""
        for b in self.batches(batch_size, **kw):
            yield b[x_key], b[y_key]

    def pairs_stream(self, x_key: str, y_key: str, batch_size: int, *,
                     seed: int = 0, shuffle: bool = True,
                     shard: tuple[int, int] | None = None
                     ) -> "FilePairs":
        """A resumable ``(x, y)`` view exposing the `batches(skip=,
        start_epoch=, batches_per_epoch=)` hook `Trainer.fit`'s
        deterministic fast-forward drives — hand THIS (not a bare
        `pairs()` generator) to ``fit(dataset=...)`` so resumes are
        byte-exact and nothing skipped is ever gathered."""
        return FilePairs(self, x_key, y_key, batch_size,
                         seed=seed, shuffle=shuffle, shard=shard)

    # --- durable stream cursors (data.stream) -------------------------------

    def stream_cursor(self, epoch: int, step: int, *, batch_size: int,
                      seed: int = 0, shuffle: bool = True,
                      repeat: bool = True,
                      shard: tuple[int, int] | None = None,
                      batches_per_epoch: int | None = None
                      ) -> "stream_lib.StreamCursor":
        """Export "``step`` batches into epoch ``epoch``" of this view's
        stream as a serializable `StreamCursor`. ``shuffle`` and
        ``repeat`` are part of the stream geometry (a shuffle=False
        stream is DIFFERENT bytes; a repeat stream is INFINITE) and are
        recorded + honoured on reconstruction — a cursor cut from a
        repeating stream reconstructs as one, never silently truncated
        at the resume epoch's boundary."""
        spec = shard if shard is not None else self._shard_spec
        return stream_lib.StreamCursor(
            kind="file", seed=int(seed), epoch=int(epoch), step=int(step),
            position={
                "n_examples": self.num_examples,
                "batch_size": int(batch_size),
                "shuffle": bool(shuffle),
                "repeat": bool(repeat),
                "shard": list(spec) if spec else None,
                "batches_per_epoch": batches_per_epoch,
            },
        )

    def batches_from(self, cursor, **kw):
        """Reconstruct the batch stream from a `StreamCursor` (or dict):
        format/kind/geometry validated loudly, then byte-exact
        continuation (`batches(skip=cursor.step, start_epoch=
        cursor.epoch, ...)`) with the CURSOR's recorded shuffle mode."""
        if not isinstance(cursor, stream_lib.StreamCursor):
            cursor = stream_lib.StreamCursor.from_dict(cursor)
        spec = kw.pop("shard", None)
        if spec is None:
            spec = self._shard_spec
        cursor.require(
            "file",
            n_examples=self.num_examples,
            shard=list(spec) if spec else None,
        )
        try:
            batch_size = int(cursor.position["batch_size"])
            if batch_size < 1:
                raise ValueError(batch_size)
        except (KeyError, TypeError, ValueError):
            raise stream_lib.StreamCursorError(
                "file cursor carries no usable batch_size — refusing to "
                "guess the stream geometry"
            ) from None
        kw.setdefault("repeat", bool(cursor.position.get("repeat", True)))
        return self.batches(
            batch_size,
            seed=cursor.seed,
            shuffle=bool(cursor.position.get("shuffle", True)),
            shard=spec,
            skip=cursor.step, start_epoch=cursor.epoch,
            batches_per_epoch=cursor.position.get("batches_per_epoch"),
            **kw,
        )


class FilePairs:
    """Resumable ``(x, y)`` stream over a `FileDataset` — the adapter
    `Trainer.fit(dataset=...)` fast-forwards through its `batches(skip=,
    start_epoch=, batches_per_epoch=)` hook (byte-exact, nothing skipped
    is gathered). Also exports/honours `StreamCursor`s."""

    def __init__(self, ds: FileDataset, x_key: str, y_key: str,
                 batch_size: int, *, seed: int = 0, shuffle: bool = True,
                 shard: tuple[int, int] | None = None):
        self.ds = ds
        self.x_key, self.y_key = x_key, y_key
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.shard = shard if shard is not None else ds.shard_spec

    def batches(self, skip: int = 0, *, start_epoch: int = 0,
                batches_per_epoch: int | None = None):
        for b in self.ds.batches(
            self.batch_size, seed=self.seed, shuffle=self.shuffle,
            shard=self.shard, skip=skip, start_epoch=start_epoch,
            batches_per_epoch=batches_per_epoch, repeat=True,
        ):
            yield b[self.x_key], b[self.y_key]

    def __iter__(self):
        return self.batches()

    def stream_cursor(self, epoch: int, step: int,
                      batches_per_epoch: int | None = None):
        return self.ds.stream_cursor(
            epoch, step, batch_size=self.batch_size, seed=self.seed,
            shuffle=self.shuffle, shard=self.shard,
            batches_per_epoch=batches_per_epoch,
        )

    def batches_from(self, cursor):
        if not isinstance(cursor, stream_lib.StreamCursor):
            cursor = stream_lib.StreamCursor.from_dict(cursor)
        # FULL geometry validation, same strictness as
        # FileDataset.batches_from: a cursor cut on a different stripe,
        # row count or shuffle mode addresses a different byte stream.
        cursor.require(
            "file", seed=self.seed,
            n_examples=self.ds.num_examples,
            batch_size=self.batch_size,
            shuffle=self.shuffle,
            shard=list(self.shard) if self.shard else None,
        )
        return self.batches(
            skip=cursor.step, start_epoch=cursor.epoch,
            batches_per_epoch=cursor.position.get("batches_per_epoch"),
        )
