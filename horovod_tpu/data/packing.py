"""Sequence packing: variable-length documents → fixed [B, T] rows + ids.

The data-side of the packed-sequence pretraining path (the reference never
has a sequence axis — SURVEY.md §5.7; this completes the framework's own
long-context story end-to-end): the flash kernel masks attention to
within-document pairs given ``segment_ids`` (ops/flash_attention.py), the
model restarts RoPE per document (models/transformer.py `packed_positions`),
and THIS module produces those ids from a real corpus of variable-length
token sequences.

Greedy first-fit packing (the standard approach — near-optimal occupancy for
natural document-length distributions at a fraction of bin-packing's cost):
documents are placed into the first open row with room, rows close when
full; leftover tail positions carry ``pad_id`` tokens in their OWN segment
(id 0) so they attend only among themselves and are maskable in the loss.

Static shapes by construction: every output row is exactly ``seq_len`` —
XLA never sees a dynamic dimension.
"""

from __future__ import annotations

import numpy as np


def pack_documents(
    docs,
    seq_len: int,
    *,
    pad_id: int = 0,
    max_docs_per_row: int | None = None,
    drop_overlong: bool = False,
):
    """Pack variable-length token sequences into fixed-length rows.

    Args:
      docs: iterable of 1-D int arrays/lists (token sequences). Documents
        longer than ``seq_len`` are split into ``seq_len`` chunks (each
        chunk its own segment) unless ``drop_overlong``.
      seq_len: row length T.
      pad_id: token filling the unused tail of each row.
      max_docs_per_row: optional cap on documents sharing one row (some
        recipes cap cross-document attention pollution of the loss mask).

    Returns:
      ``(tokens, segment_ids, doc_ids)`` — all ``[n_rows, seq_len]`` int32:
      * ``tokens``: packed token rows;
      * ``segment_ids``: 1-based per-row segment numbering, 0 = padding —
        feed straight into ``TransformerLM(..., segment_ids=...)`` /
        `flash_attention`;
      * ``doc_ids``: index into ``docs`` for each position (-1 = padding) —
        for bookkeeping/metrics, not consumed by the model.
    """
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    pieces: list[tuple[int, np.ndarray]] = []
    for i, d in enumerate(docs):
        arr = np.asarray(d, np.int32).reshape(-1)
        if len(arr) == 0:
            continue
        if len(arr) > seq_len:
            if drop_overlong:
                continue
            for s in range(0, len(arr), seq_len):
                chunk = arr[s : s + seq_len]
                if len(chunk):
                    pieces.append((i, chunk))
        else:
            pieces.append((i, arr))

    # Best-fit-decreasing: longest pieces first, each placed into the open
    # row with the SMALLEST remaining capacity that still fits — found by
    # bisect over a (remaining, row) list kept sorted, so placement is
    # O(log rows) per piece instead of a linear scan (a 1e6-document corpus
    # packs in seconds, not hours). Occupancy matches or beats first-fit.
    import bisect

    pieces.sort(key=lambda p: -len(p[1]))
    rows: list[list[tuple[int, np.ndarray]]] = []
    open_rows: list[tuple[int, int]] = []  # sorted (remaining, row_index)

    def reinsert(r: int, remaining: int) -> None:
        if remaining > 0 and (
            max_docs_per_row is None or len(rows[r]) < max_docs_per_row
        ):
            bisect.insort(open_rows, (remaining, r))

    for i, arr in pieces:
        k = bisect.bisect_left(open_rows, (len(arr), -1))
        if k < len(open_rows):
            remaining, r = open_rows.pop(k)
            rows[r].append((i, arr))
            reinsert(r, remaining - len(arr))
        else:
            rows.append([(i, arr)])
            reinsert(len(rows) - 1, seq_len - len(arr))

    n = len(rows)
    tokens = np.full((n, seq_len), pad_id, np.int32)
    segment_ids = np.zeros((n, seq_len), np.int32)
    doc_ids = np.full((n, seq_len), -1, np.int32)
    for r, row in enumerate(rows):
        at = 0
        for s, (i, arr) in enumerate(row, start=1):
            tokens[r, at : at + len(arr)] = arr
            segment_ids[r, at : at + len(arr)] = s
            doc_ids[r, at : at + len(arr)] = i
            at += len(arr)
    return tokens, segment_ids, doc_ids


def packing_efficiency(segment_ids) -> float:
    """Fraction of positions carrying real (non-padding) tokens."""
    seg = np.asarray(segment_ids)
    return float((seg != 0).mean()) if seg.size else 0.0


def next_token_pairs(tokens, segment_ids):
    """(x, y, weights) next-token training triplets for packed rows.

    ``y`` is ``tokens`` shifted left within the row; ``weights`` zeroes the
    positions whose TARGET crosses a document boundary or is padding (both
    decided purely by ``segment_ids`` — padding is segment 0) — the
    per-token loss mask packed pretraining needs (multiply into a per-token
    loss, or feed frameworks that take sample weights)."""
    toks = np.asarray(tokens, np.int32)
    seg = np.asarray(segment_ids, np.int32)
    x = toks[:, :-1]
    y = toks[:, 1:]
    w = (
        (seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] != 0)
    ).astype(np.float32)
    return x, y, w
