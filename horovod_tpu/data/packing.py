"""Sequence packing: variable-length documents → fixed [B, T] rows + ids.

The data-side of the packed-sequence pretraining path (the reference never
has a sequence axis — SURVEY.md §5.7; this completes the framework's own
long-context story end-to-end): the flash kernel masks attention to
within-document pairs given ``segment_ids`` (ops/flash_attention.py), the
model restarts RoPE per document (models/transformer.py `packed_positions`),
and THIS module produces those ids from a real corpus of variable-length
token sequences.

Greedy first-fit packing (the standard approach — near-optimal occupancy for
natural document-length distributions at a fraction of bin-packing's cost):
documents are placed into the first open row with room, rows close when
full; leftover tail positions carry ``pad_id`` tokens in their OWN segment
(id 0) so they attend only among themselves and are maskable in the loss.

Static shapes by construction: every output row is exactly ``seq_len`` —
XLA never sees a dynamic dimension.
"""

from __future__ import annotations

import numpy as np


def pack_documents(
    docs,
    seq_len: int,
    *,
    pad_id: int = 0,
    max_docs_per_row: int | None = None,
    drop_overlong: bool = False,
):
    """Pack variable-length token sequences into fixed-length rows.

    Args:
      docs: iterable of 1-D int arrays/lists (token sequences). Documents
        longer than ``seq_len`` are split into ``seq_len`` chunks (each
        chunk its own segment) unless ``drop_overlong``.
      seq_len: row length T.
      pad_id: token filling the unused tail of each row.
      max_docs_per_row: optional cap on documents sharing one row (some
        recipes cap cross-document attention pollution of the loss mask).

    Returns:
      ``(tokens, segment_ids, doc_ids)`` — all ``[n_rows, seq_len]`` int32:
      * ``tokens``: packed token rows;
      * ``segment_ids``: 1-based per-row segment numbering, 0 = padding —
        feed straight into ``TransformerLM(..., segment_ids=...)`` /
        `flash_attention`;
      * ``doc_ids``: index into ``docs`` for each position (-1 = padding) —
        for bookkeeping/metrics, not consumed by the model.
    """
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    pieces: list[tuple[int, np.ndarray]] = []
    for i, d in enumerate(docs):
        arr = np.asarray(d, np.int32).reshape(-1)
        if len(arr) == 0:
            continue
        if len(arr) > seq_len:
            if drop_overlong:
                continue
            for s in range(0, len(arr), seq_len):
                chunk = arr[s : s + seq_len]
                if len(chunk):
                    pieces.append((i, chunk))
        else:
            pieces.append((i, arr))

    # Best-fit-decreasing: longest pieces first, each placed into the open
    # row with the SMALLEST remaining capacity that still fits — found by
    # bisect over a (remaining, row) list kept sorted, so placement is
    # O(log rows) per piece instead of a linear scan (a 1e6-document corpus
    # packs in seconds, not hours). Occupancy matches or beats first-fit.
    import bisect

    pieces.sort(key=lambda p: -len(p[1]))
    rows: list[list[tuple[int, np.ndarray]]] = []
    open_rows: list[tuple[int, int]] = []  # sorted (remaining, row_index)

    def reinsert(r: int, remaining: int) -> None:
        if remaining > 0 and (
            max_docs_per_row is None or len(rows[r]) < max_docs_per_row
        ):
            bisect.insort(open_rows, (remaining, r))

    for i, arr in pieces:
        k = bisect.bisect_left(open_rows, (len(arr), -1))
        if k < len(open_rows):
            remaining, r = open_rows.pop(k)
            rows[r].append((i, arr))
            reinsert(r, remaining - len(arr))
        else:
            rows.append([(i, arr)])
            reinsert(len(rows) - 1, seq_len - len(arr))

    n = len(rows)
    tokens = np.full((n, seq_len), pad_id, np.int32)
    segment_ids = np.zeros((n, seq_len), np.int32)
    doc_ids = np.full((n, seq_len), -1, np.int32)
    for r, row in enumerate(rows):
        at = 0
        for s, (i, arr) in enumerate(row, start=1):
            tokens[r, at : at + len(arr)] = arr
            segment_ids[r, at : at + len(arr)] = s
            doc_ids[r, at : at + len(arr)] = i
            at += len(arr)
    return tokens, segment_ids, doc_ids


def packing_efficiency(segment_ids) -> float:
    """Fraction of positions carrying real (non-padding) tokens."""
    seg = np.asarray(segment_ids)
    return float((seg != 0).mean()) if seg.size else 0.0


class PackedLMStream:
    """Resumable packed-LM batch stream — the corpus → packed-row feeding
    path (`tokenizer.py` → `pack_documents` → `next_token_pairs`) as a
    DURABLE stream with an exportable `data.stream.StreamCursor`.

    Packing is deterministic (best-fit-decreasing over a fixed corpus
    order), so the packed row set is a pure function of the inputs; the
    per-epoch row order is a pure function of ``(seed, epoch)`` (the
    anchored `ArrayDataset` engine underneath). Together any stream
    position — including epochs consumed by a dead process — is
    reconstructible byte-exactly from ``(seed, epoch, step)`` plus the
    geometry fingerprint the cursor carries (row count, seq_len, batch
    size, shard spec, and the tokenizer's merge-table sha256 when the
    corpus came in as raw text).

    Batches are ``(x, y)`` with ``x = tokens ⊕ segment_ids`` ([B, T, 2]
    int32) and ``y = targets ⊕ loss-weights`` ([B, T, 2] int32) — the
    `examples/lm_packed_pretraining.py` stacked-channel feed, so the
    stream drops straight into ``Trainer.fit(dataset=...)`` with the
    masked-CE loss."""

    def __init__(self, docs, seq_len: int, batch_size: int, *,
                 seed: int = 0, tokenizer=None, shard=(0, 1),
                 pad_id: int = 0):
        self._tok_digest = None
        if tokenizer is not None:
            import hashlib
            import json as _json

            self._tok_digest = hashlib.sha256(
                _json.dumps(
                    [list(m) for m in tokenizer.merges]
                ).encode()
            ).hexdigest()[:16]
            docs = tokenizer.encode_corpus(docs)
        toks, seg, _ = pack_documents(docs, seq_len + 1, pad_id=pad_id)
        x, y, w = next_token_pairs(toks, seg)
        xs = np.stack([x, seg[:, :-1]], axis=-1)
        ys = np.stack([y, w.astype(np.int32)], axis=-1)
        self.seq_len = int(seq_len)
        self.n_rows = int(len(xs))
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        from horovod_tpu.data.loader import ArrayDataset

        ds = ArrayDataset((xs, ys))
        if tuple(shard) != (0, 1):
            ds = ds.shard(*shard)
        self.shard = tuple(shard)
        self._ds = (
            ds.repeat()
            .shuffle(ds.num_examples, seed=seed)
            .batch(batch_size)
        )

    def batches(self, skip: int = 0, *, start_epoch: int = 0,
                batches_per_epoch: int | None = None):
        """Anchored ``(x, y)`` batches — the `Trainer.fit(dataset=...)`
        fast-forward hook (see `ArrayDataset.batches`)."""
        return self._ds.batches(
            skip=skip, start_epoch=start_epoch,
            batches_per_epoch=batches_per_epoch,
        )

    def __iter__(self):
        return self.batches()

    def stream_cursor(self, epoch: int, step: int,
                      batches_per_epoch: int | None = None):
        from horovod_tpu.data import stream as stream_lib

        return stream_lib.StreamCursor(
            kind="packed-lm", seed=self.seed, epoch=int(epoch),
            step=int(step),
            position={
                "n_rows": self.n_rows,
                "seq_len": self.seq_len,
                "batch_size": self.batch_size,
                "shard": list(self.shard),
                "tokenizer_sha256": self._tok_digest,
                "batches_per_epoch": batches_per_epoch,
            },
        )

    def batches_from(self, cursor):
        """Byte-exact continuation from a `StreamCursor` (or its dict
        form); format/kind/geometry mismatches are refused loudly."""
        from horovod_tpu.data import stream as stream_lib

        if not isinstance(cursor, stream_lib.StreamCursor):
            cursor = stream_lib.StreamCursor.from_dict(cursor)
        cursor.require(
            "packed-lm", seed=self.seed,
            n_rows=self.n_rows, seq_len=self.seq_len,
            batch_size=self.batch_size, shard=list(self.shard),
            tokenizer_sha256=self._tok_digest,
        )
        return self.batches(
            skip=cursor.step, start_epoch=cursor.epoch,
            batches_per_epoch=cursor.position.get("batches_per_epoch"),
        )


def next_token_pairs(tokens, segment_ids):
    """(x, y, weights) next-token training triplets for packed rows.

    ``y`` is ``tokens`` shifted left within the row; ``weights`` zeroes the
    positions whose TARGET crosses a document boundary or is padding (both
    decided purely by ``segment_ids`` — padding is segment 0) — the
    per-token loss mask packed pretraining needs (multiply into a per-token
    loss, or feed frameworks that take sample weights)."""
    toks = np.asarray(tokens, np.int32)
    seg = np.asarray(segment_ids, np.int32)
    x = toks[:, :-1]
    y = toks[:, 1:]
    w = (
        (seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] != 0)
    ).astype(np.float32)
    return x, y, w
