"""Data layer: dataset registry + per-process sharded input pipeline."""

from horovod_tpu.data.datasets import mnist, cifar10  # noqa: F401
from horovod_tpu.data.loader import ArrayDataset, training_pipeline  # noqa: F401
from horovod_tpu.data.native_loader import NativeBatchLoader  # noqa: F401
from horovod_tpu.data.native_loader import available as native_available  # noqa: F401
from horovod_tpu.data.packing import (  # noqa: F401
    PackedLMStream,
    next_token_pairs,
    pack_documents,
    packing_efficiency,
)
from horovod_tpu.data.stream import (  # noqa: F401
    StreamCursor,
    StreamCursorError,
    epoch_seed,
)

# The distributed data service (PR 20): dispatcher + trainer-side client.
# Imported lazily-by-name here, not at package import — the service module
# is socket/daemon machinery most training paths never touch.


def __getattr__(name):
    if name in ("ServiceClient", "build_source"):
        from horovod_tpu.data import client as _client

        return getattr(_client, name)
    if name == "DataService":
        from horovod_tpu.data import service as _service

        return _service.DataService
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
