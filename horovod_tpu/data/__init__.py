"""Data layer: dataset registry + per-process sharded input pipeline."""

from horovod_tpu.data.datasets import mnist, cifar10  # noqa: F401
from horovod_tpu.data.loader import ArrayDataset, training_pipeline  # noqa: F401
from horovod_tpu.data.native_loader import NativeBatchLoader  # noqa: F401
from horovod_tpu.data.native_loader import available as native_available  # noqa: F401
from horovod_tpu.data.packing import (  # noqa: F401
    PackedLMStream,
    next_token_pairs,
    pack_documents,
    packing_efficiency,
)
from horovod_tpu.data.stream import (  # noqa: F401
    StreamCursor,
    StreamCursorError,
    epoch_seed,
)
