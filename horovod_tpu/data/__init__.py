"""Data layer: dataset registry + per-process sharded input pipeline."""

from horovod_tpu.data.datasets import mnist, cifar10  # noqa: F401
from horovod_tpu.data.loader import ArrayDataset  # noqa: F401
