"""Durable stream cursors — byte-exact cross-epoch resume for the feeders.

PR 5 made `fit(initial_step=)` step-exact WITHIN the resume epoch, but the
streamed feeding paths re-anchored epochs that predate the resume call:
each fit built a fresh shuffle stream whose RNG state evolved ACROSS
epochs, so "epoch 40 of a resumed run" and "epoch 40 of the uninterrupted
run" drew different permutations. The fix implemented across the data
layer is positional addressability: every feeding engine derives the
order of epoch ``e`` (and pass ``p`` within it) as a PURE FUNCTION of
``(seed, e, p)`` — `epoch_seed` here, `mix_seed` in the native engine —
so any position in the infinite stream is reconstructible without
replaying the stream that led to it.

With that invariant, a stream position is fully described by a small
serializable record, the `StreamCursor`:

* ``kind`` — which engine produced it (``array``/``file``/``native``/
  ``packed-lm``/``fit``); a cursor never resumes a different engine.
* ``seed``/``epoch``/``step`` — the anchored position: ``step`` counts
  BATCHES consumed within ``epoch``.
* ``position`` — per-source geometry (example count, batch size, shard
  spec, batches-per-epoch, ...): the stream is only byte-identical when
  the geometry matches, so reconstruction validates it loudly.
* ``format`` — the cursor format version. A cursor from a DIFFERENT
  format version is REFUSED loudly (`StreamCursorError`), never silently
  re-anchored: a silently re-anchored resume is exactly the corruption
  this subsystem exists to prevent.

The cursor rides the existing durability surfaces: checkpoint progress
manifests (``.meta.json`` / sharded ``index.json`` — `checkpoint.save*`
``cursor=``), `ElasticState` commits (tracked ``cursor`` attribute), and
`Trainer.fit(initial_epoch=, initial_step=)` threading.

This module also owns the transient-I/O hardening for the file-backed
feeders: `read_with_retries` wraps mmap/index reads in a bounded
retry-with-backoff (`HVT_DATA_RETRIES` × `HVT_DATA_BACKOFF_S`,
exponential), failing fast with the actionable checkpoint-fallback
message once the budget is spent. `HVT_DATA_FAULT_READS` injects
deterministic transient faults for the chaos tests.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from horovod_tpu.analysis import registry

# Bump when the anchored-stream derivation changes incompatibly (a new
# epoch_seed scheme, a different pass-rollover rule): a cursor written by
# an older format addresses positions in a DIFFERENT byte stream, so
# resuming from it must fail loudly, never silently re-anchor.
CURSOR_FORMAT = 1


class StreamCursorError(ValueError):
    """A stream cursor cannot be honoured byte-exactly (wrong format
    version, wrong engine kind, or mismatched stream geometry)."""


def epoch_seed(seed: int, epoch: int, pass_: int = 0) -> int:
    """The RNG seed for pass ``pass_`` of epoch ``epoch`` of a stream
    seeded ``seed`` — the pure derivation that makes stream positions
    addressable. `numpy.random.SeedSequence` is documented stable across
    numpy versions, so the derived streams are reproducible artifacts.
    (The native engine uses its own splitmix64 derivation with the same
    (seed, epoch, pass) purity — byte-identity is per-engine.)"""
    return int(
        np.random.SeedSequence(
            [int(seed) & 0xFFFFFFFF, int(epoch), int(pass_)]
        ).generate_state(1)[0]
    )


@dataclasses.dataclass
class StreamCursor:
    """One serializable stream position. See the module docstring."""

    kind: str
    seed: int
    epoch: int
    step: int               # batches consumed within `epoch`
    position: dict          # per-source geometry the stream depends on
    format: int = CURSOR_FORMAT

    def to_dict(self) -> dict:
        """JSON-ready form (what checkpoint manifests store)."""
        return {
            "format": int(self.format),
            "kind": self.kind,
            "seed": int(self.seed),
            "epoch": int(self.epoch),
            "step": int(self.step),
            "position": dict(self.position),
        }

    @classmethod
    def from_dict(cls, rec: dict) -> "StreamCursor":
        """Parse a stored cursor. REFUSES unknown/older format versions
        loudly — resuming a v(N) stream from a v(M) cursor would silently
        re-anchor the byte stream, the exact corruption cursors exist to
        prevent. Recover by resuming epoch-granular (``initial_epoch``
        from the progress manifest, ``initial_step=0``) instead."""
        if not isinstance(rec, dict) or "format" not in rec:
            raise StreamCursorError(
                "not a stream cursor record (missing 'format'); refusing "
                "to guess a stream position"
            )
        fmt = int(rec["format"])
        if fmt != CURSOR_FORMAT:
            raise StreamCursorError(
                f"stream cursor format {fmt} != this build's "
                f"{CURSOR_FORMAT}: the anchored-stream derivation changed "
                "and this cursor addresses a DIFFERENT byte stream. "
                "Refusing to silently re-anchor — resume epoch-granular "
                "(initial_epoch from the progress manifest, "
                "initial_step=0) or re-train from the last checkpoint "
                "written by this build."
            )
        return cls(
            kind=str(rec["kind"]),
            seed=int(rec["seed"]),
            epoch=int(rec["epoch"]),
            step=int(rec["step"]),
            position=dict(rec.get("position", {})),
            format=fmt,
        )

    def require(self, kind: str, **geometry) -> None:
        """Validate this cursor against the reconstructing stream: same
        engine kind, same seed, same geometry — byte-identity holds only
        then. Raises `StreamCursorError` naming the first mismatch."""
        if self.kind != kind:
            raise StreamCursorError(
                f"cursor was exported by a {self.kind!r} stream, cannot "
                f"resume a {kind!r} stream byte-exactly"
            )
        want_seed = geometry.pop("seed", None)
        if want_seed is not None and int(want_seed) != self.seed:
            raise StreamCursorError(
                f"cursor seed {self.seed} != stream seed {int(want_seed)} "
                "— different shuffle streams"
            )
        for key, want in geometry.items():
            got = self.position.get(key)
            # JSON round-trips tuples as lists; compare canonicalized.
            def canon(v):
                return list(v) if isinstance(v, (tuple, list)) else v
            if canon(got) != canon(want):
                raise StreamCursorError(
                    f"cursor geometry mismatch at {key!r}: cursor has "
                    f"{got!r}, the stream has {want!r} — the data or its "
                    "sharding changed since the cursor was written"
                )


# --- transient-I/O hardening -------------------------------------------------

# Observable retry telemetry (tests assert it; ops can log it): total
# transient read failures retried since import, and total reads whose
# retry budget was EXHAUSTED (the degrade/fail-fast escalations).
# Exported per outcome as `hvt_data_retries_total{outcome=...}` by the
# trainer exporter's collector (obs/server.py) — a silently retrying
# fleet must not look healthy on /metrics.
RETRY_STATS = {"retried": 0, "exhausted": 0}

# Deterministic fault injection for the chaos tests: the first
# HVT_DATA_FAULT_READS guarded reads raise a (retriable) OSError. Lazily
# armed from the knob so a test's monkeypatched env is honoured.
_fault_budget: int | None = None


def _take_injected_fault() -> bool:
    global _fault_budget
    if _fault_budget is None:
        _fault_budget = registry.get_int("HVT_DATA_FAULT_READS") or 0
    if _fault_budget > 0:
        _fault_budget -= 1
        return True
    return False


def reset_fault_injection() -> None:
    """Re-arm `HVT_DATA_FAULT_READS` from the environment (test hook)."""
    global _fault_budget
    _fault_budget = None


def read_with_retries(fn, what: str):
    """Run ``fn()`` (a dataset read: an mmap open, an index load) with
    bounded retry-with-backoff on TRANSIENT failures.

    Retriable: `OSError` (the NFS/FUSE/flaky-disk class — EIO, ESTALE,
    EAGAIN, a vanished-then-replaced file). Up to ``HVT_DATA_RETRIES``
    retries, sleeping ``HVT_DATA_BACKOFF_S × 2**attempt`` between
    attempts. Anything else (a ValueError from a genuinely corrupt index,
    a KeyboardInterrupt) propagates immediately — retrying non-transient
    errors only delays the real diagnosis.

    Exhausted budget fails FAST with the actionable escalation: the run
    should fall back to its newest checkpoint (restart under the
    supervisor), not spin on a dead filesystem."""
    retries = registry.get_int("HVT_DATA_RETRIES")
    retries = 3 if retries is None else max(0, int(retries))
    backoff = registry.get_float("HVT_DATA_BACKOFF_S")
    backoff = 0.05 if backoff is None else max(0.0, float(backoff))
    last: OSError | None = None
    for attempt in range(retries + 1):
        try:
            if _take_injected_fault():
                raise OSError(
                    f"injected transient read fault (HVT_DATA_FAULT_READS) "
                    f"reading {what}"
                )
            return fn()
        except OSError as e:
            last = e
            if attempt < retries:
                RETRY_STATS["retried"] += 1
                time.sleep(backoff * (2 ** attempt))
    RETRY_STATS["exhausted"] += 1
    raise RuntimeError(
        f"transient I/O failure reading {what} persisted through "
        f"{retries} retr{'y' if retries == 1 else 'ies'} "
        f"(HVT_DATA_RETRIES; last error: {last}). The data source is "
        "unavailable — fail fast and restart this run from its newest "
        "checkpoint (the supervisor relaunch path); raise "
        "HVT_DATA_RETRIES/HVT_DATA_BACKOFF_S if the filesystem is known "
        "to blip longer than the current budget."
    ) from last
