"""Trainer-side client for the hvt-data dispatcher (`data.service`).

`ServiceClient` is a drop-in anchored-batches source: it exposes the
same ``batches(skip=, start_epoch=, batches_per_epoch=)`` hook
`Trainer.fit(dataset=)` probes for, so a fit is service-fed with zero
trainer changes — the client is just another positionally-addressable
stream.

The client owns a LOCAL copy of the source (`service.build_source` on
the same spec the dispatcher admits), which buys the two failover
properties the tentpole demands:

* **Bounded-retry fetches.** Every service interaction — connect, hello,
  next — runs under `stream.read_with_retries` (the
  ``HVT_DATA_RETRIES`` × ``HVT_DATA_BACKOFF_S`` discipline): transient
  socket failures (a dispatcher restarting, a dropped connection) are
  absorbed, each retry re-attaching from the CURRENT cursor, so a
  dispatcher that comes back serves the exact next batch.
* **Graceful degradation.** When the budget is exhausted the client
  falls back to rank-local feeding *from the same cursor* — byte-
  identically, because local and served streams are the same pure
  ``(seed, epoch, pass)`` derivation — and re-attaches to the service at
  the next epoch boundary. A data-plane outage slows the fit; it never
  corrupts or kills it.

Re-attach hellos carry NO spec: the dispatcher must know the job from
its own memory or its admission journal — which is what makes a
successful re-attach after a dispatcher SIGKILL the proof of journal
recovery. `StreamCursor` refusals coming back over the wire re-raise as
`StreamCursorError` (loud, never retried, never silently re-anchored).

Knobs: ``HVT_DATA_SERVICE`` (``host:port``; unset → the client is a
pure local passthrough), ``HVT_DATA_JOB`` (admission name, default
"default"), ``HVT_DATA_TIMEOUT_S`` (per-socket-op timeout).

The ``netdrop:MS`` chaos fault (`testing.faults`) is applied HERE — a
client-side connection drop plus reconnect delay before each fetch
during the fault's target epoch — because the trainer callback cannot
reach into the data plane's sockets.
"""

from __future__ import annotations

import socket
import time

import numpy as np

from horovod_tpu.analysis import registry
from horovod_tpu.data import service as service_lib
from horovod_tpu.data import stream as stream_lib
from horovod_tpu.obs import core as obs_core

build_source = service_lib.build_source  # re-export: the shared recipe


class ServiceClient:
    """A service-fed anchored-batches source with byte-exact local
    fallback. ``source`` is the local `ArrayDataset` chain (built from
    the SAME ``spec`` the dispatcher is given); ``shard`` is this rank's
    ``(index, count)`` split — its index doubles as the fault-plan rank
    for the ``netdrop`` chaos kind."""

    def __init__(self, source, spec: dict | None = None, *,
                 job: str | None = None, shard=(0, 1),
                 address: str | None = None):
        self.source = source
        self.spec = dict(spec) if spec is not None else None
        self.job = job or registry.get_str("HVT_DATA_JOB") or "default"
        self.shard = (int(shard[0]), int(shard[1]))
        if address is None:
            address = registry.get_str("HVT_DATA_SERVICE")
        self.address = address or None
        timeout = registry.get_float("HVT_DATA_TIMEOUT_S")
        self.timeout = 5.0 if timeout is None else float(timeout)
        self._sock: socket.socket | None = None
        self._ever_admitted = False
        # Failover audit trail (the chaos e2e asserts on it): dicts of
        # {"event": "degrade"|"reattach", "epoch": e, "step": s, ...}.
        self.events: list[dict] = []

    # -- connection management ------------------------------------------------

    def _close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        self._close()

    def _connect(self) -> socket.socket:
        host, _, port = self.address.rpartition(":")
        sock = socket.create_connection(
            (host, int(port)), timeout=self.timeout
        )
        return sock

    def _netdrop(self, epoch: int) -> None:
        from horovod_tpu.testing import faults

        ms = faults.data_fault_ms(
            "netdrop", epoch=epoch, rank=self.shard[0]
        )
        if ms is not None:
            self._close()
            time.sleep(ms / 1e3)
            raise OSError(
                "injected connection drop (HVT_FAULT netdrop) — "
                f"reconnect delayed {ms:g} ms"
            )

    def _roundtrip(self, header: dict, epoch: int) -> tuple[dict, bytes]:
        """One request/response on the live connection, re-attaching
        first if there is none. Raises OSError on any transport failure
        (retriable) and `StreamCursorError` on a wire refusal (loud,
        final)."""
        self._netdrop(epoch)
        if self._sock is None:
            self._sock = self._connect()
            try:
                self._hello()
            except BaseException:
                self._close()
                raise
        try:
            service_lib.send_frame(self._sock, header)
            resp, payload = service_lib.recv_frame(self._sock)
        except OSError:
            self._close()
            raise
        if resp is None:
            self._close()
            raise OSError("hvt-data service closed the connection")
        if not resp.get("ok"):
            if resp.get("refusal"):
                raise stream_lib.StreamCursorError(
                    f"hvt-data service refused the presented cursor: "
                    f"{resp.get('error')}"
                )
            self._close()
            raise OSError(f"hvt-data service error: {resp.get('error')}")
        return resp, payload

    def _hello(self) -> None:
        """Attach this (job, shard) on the fresh connection. The FIRST
        successful attach carries the source spec (the admission); every
        later one carries none — adopting must come from the
        dispatcher's memory or journal."""
        hello = {
            "op": "hello", "job": self.job, "shard": list(self.shard),
        }
        if not self._ever_admitted:
            if self.spec is None:
                raise ValueError(
                    "ServiceClient needs a source spec for its first "
                    "admission (spec=...)"
                )
            hello["spec"] = self.spec
        service_lib.send_frame(self._sock, hello)
        resp, _ = service_lib.recv_frame(self._sock)
        if resp is None:
            raise OSError("connection closed during hvt-data hello")
        if not resp.get("ok"):
            if resp.get("refusal"):
                raise stream_lib.StreamCursorError(
                    f"hvt-data service refused this client's stream: "
                    f"{resp.get('error')}"
                )
            raise OSError(f"hvt-data hello failed: {resp.get('error')}")
        self._ever_admitted = True

    # -- batch transport ------------------------------------------------------

    def _cursor(self, epoch: int, step: int,
                batches_per_epoch: int | None):
        return self.source.stream_cursor(
            epoch, step, batches_per_epoch=batches_per_epoch
        )

    def _decode(self, resp: dict, payload: bytes):
        leaves = []
        offset = 0
        for leaf in resp["leaves"]:
            dt = np.dtype(leaf["dtype"])
            shape = tuple(int(d) for d in leaf["shape"])
            count = int(np.prod(shape)) if shape else 1
            a = np.frombuffer(
                payload, dtype=dt, count=count, offset=offset
            ).reshape(shape)
            offset += a.nbytes
            leaves.append(np.array(a))  # writable copy off the buffer
        structure = getattr(self.source, "structure", None)
        if structure is not None:
            import jax.tree_util

            return jax.tree_util.tree_unflatten(structure, leaves)
        return tuple(leaves) if len(leaves) != 1 else leaves[0]

    def _fetch(self, epoch: int, step: int,
               batches_per_epoch: int | None):
        """One served batch at (epoch, step), under the bounded-retry
        budget. RuntimeError = budget exhausted (the degrade trigger);
        StreamCursorError = wire refusal (propagates loudly)."""
        cursor = self._cursor(epoch, step, batches_per_epoch).to_dict()

        def do():
            resp, payload = self._roundtrip({
                "op": "next", "job": self.job,
                "shard": list(self.shard), "cursor": cursor,
            }, epoch)
            return self._decode(resp, payload)

        return stream_lib.read_with_retries(
            do,
            f"hvt-data batch (job {self.job!r}, epoch {epoch}, "
            f"step {step}) from {self.address}",
        )

    def _try_reattach(self, epoch: int) -> bool:
        """One epoch-boundary re-attach attempt (single shot, no retry
        budget — a down service just means one more local epoch)."""
        try:
            self._netdrop(epoch)
            self._sock = self._connect()
            self._hello()
            return True
        except (OSError, ValueError):
            self._close()
            return False

    # -- the anchored-batches hook --------------------------------------------

    def batches(self, skip: int = 0, *, start_epoch: int = 0,
                batches_per_epoch: int | None = None):
        """The `run_fit` anchored-batches contract. Service-fed while
        attached; on an exhausted retry budget, degrades to the LOCAL
        source from the same cursor (byte-identical by construction) and
        re-attaches at the next epoch boundary."""
        B = int(batches_per_epoch) if batches_per_epoch else None
        epoch, step = int(start_epoch), int(skip)
        if B:
            epoch, step = epoch + step // B, step % B
        local_it = None
        if self.address is None:
            # No service configured: a pure local passthrough — the
            # degraded mode IS the normal mode.
            local_it = self._local_iter(epoch, step, B)
        while True:
            if local_it is not None:
                batch = next(local_it)
            else:
                try:
                    batch = self._fetch(epoch, step, B)
                except RuntimeError as e:
                    self._degrade(epoch, step, e)
                    local_it = self._local_iter(epoch, step, B)
                    batch = next(local_it)
            yield batch
            step += 1
            if B and step >= B:
                epoch, step = epoch + 1, 0
                if local_it is not None and self.address is not None:
                    if self._try_reattach(epoch):
                        obs_core.counter("hvt_data_reattach_total")
                        self.events.append({
                            "event": "reattach", "epoch": epoch,
                            "step": step,
                        })
                        print(
                            f"hvt-data client: re-attached to "
                            f"{self.address} at epoch {epoch} "
                            f"(job {self.job!r})",
                            flush=True,
                        )
                        local_it = None

    def _local_iter(self, epoch: int, step: int, B: int | None):
        return self.source.batches(
            skip=step, start_epoch=epoch, batches_per_epoch=B
        )

    def _degrade(self, epoch: int, step: int, err: Exception) -> None:
        self._close()
        obs_core.counter("hvt_data_degraded_total")
        self.events.append({
            "event": "degrade", "epoch": epoch, "step": step,
            "error": str(err),
        })
        print(
            f"hvt-data client: retry budget exhausted at epoch {epoch} "
            f"step {step} — degrading to rank-local feeding from the "
            f"same cursor (byte-identical); will re-attach at the next "
            f"epoch boundary ({err})",
            flush=True,
        )

    def __iter__(self):
        return self.batches()
