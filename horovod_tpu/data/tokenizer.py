"""Byte-level BPE tokenizer — the text front-end of the LM data pipeline.

The reference has no text path at all (MNIST images only); a framework
whose flagship families are language models needs corpus → token-id
plumbing, so this module completes the chain
``text → ByteBPETokenizer.encode → packing.pack_documents →
TransformerLM(segment_ids=...)`` with zero external dependencies.

Byte-level BPE (the GPT-2/RoBERTa scheme, Sennrich et al. arXiv:1508.07909
adapted to bytes): the base alphabet is all 256 bytes — every string is
encodable with NO unknown-token case, and ``decode(encode(s)) == s``
exactly for any Unicode input. Training learns ``vocab_size − 256 −
len(specials)`` merges by iterated most-frequent-pair counting over a
word-frequency table; encoding applies those merges greedily by learned
rank (lowest rank first — the standard BPE inference order), with an
LRU-ish per-word cache since natural corpora repeat words heavily.

Pre-tokenization splits on whitespace with the space attached to the
FOLLOWING word (GPT-2's convention, so ``" the"`` is one frequent unit
and merges never cross word boundaries — what keeps BPE training linear
instead of corpus-quadratic).

Special tokens occupy the id range [256 + n_merges, vocab_size) and are
matched as whole literals before byte-splitting, so ``<eos>`` in raw text
becomes one id, never 5 byte tokens.
"""

from __future__ import annotations

import collections
import heapq
import json
import os

import numpy as np

from horovod_tpu.data import stream as stream_lib


def _pretokenize(text: str) -> list[bytes]:
    """Whitespace-split with the space glued to the next word: the units
    BPE merges operate within."""
    words: list[bytes] = []
    start = 0
    i = 0
    n = len(text)
    while i < n:
        if text[i].isspace():
            # Flush the word ending here; the whitespace run prefixes the
            # next word.
            if start < i:
                words.append(text[start:i].encode("utf-8"))
                start = i
            i += 1
            while i < n and text[i].isspace():
                i += 1
            # find the end of the following word
            j = i
            while j < n and not text[j].isspace():
                j += 1
            words.append(text[start:j].encode("utf-8"))
            start = j
            i = j
        else:
            i += 1
    if start < n:
        words.append(text[start:].encode("utf-8"))
    return words


class ByteBPETokenizer:
    """Trainable byte-level BPE. ``train`` then ``encode``/``decode``;
    `save`/`load` round-trip the full state as JSON."""

    def __init__(self, merges=None, specials=()):
        # merges: list of (id_a, id_b) pairs in learned order; pair i forms
        # token id 256 + i.
        self.merges: list[tuple[int, int]] = [tuple(m) for m in (merges or [])]
        self.specials: tuple[str, ...] = tuple(specials)
        self._ranks = {m: i for i, m in enumerate(self.merges)}
        self._cache: dict[bytes, list[int]] = {}

    # -- vocabulary layout ---------------------------------------------------
    @property
    def vocab_size(self) -> int:
        return 256 + len(self.merges) + len(self.specials)

    def special_id(self, token: str) -> int:
        return 256 + len(self.merges) + self.specials.index(token)

    # -- training ------------------------------------------------------------
    @classmethod
    def train(cls, texts, vocab_size: int, specials=()) -> "ByteBPETokenizer":
        """Learn merges from an iterable of strings until ``vocab_size``.

        Pair counting runs over the word-frequency table (each distinct
        word counted once, weighted by its frequency) — corpus length only
        matters through the pre-tokenization pass.
        """
        n_merges = vocab_size - 256 - len(specials)
        if n_merges < 0:
            raise ValueError(
                f"vocab_size ({vocab_size}) < base 256 + specials "
                f"({len(specials)})"
            )
        word_freq: collections.Counter = collections.Counter()
        for t in texts:
            word_freq.update(_pretokenize(t))
        # Each distinct word as a mutable symbol list. Training is
        # incremental (the merge-queue scheme): pair counts and a
        # pair → containing-words index are built once, each merge touches
        # only the words that contain the merged pair, and the best pair
        # comes from a lazy-deletion heap — per-merge cost is O(changed)
        # instead of a full corpus rescan, which is what makes MB-scale
        # corpora train in seconds.
        words = [(list(w), f) for w, f in word_freq.items()]
        pairs: dict[tuple[int, int], int] = {}
        where: dict[tuple[int, int], set[int]] = {}
        for wi, (sym, f) in enumerate(words):
            for p in zip(sym, sym[1:]):
                pairs[p] = pairs.get(p, 0) + f
                where.setdefault(p, set()).add(wi)
        # Heap key (-count, pair) reproduces the selection order of a full
        # rescan: highest count first, ties to the smallest (a, b) — the
        # learned merges are bit-identical to the O(merges × corpus)
        # trainer this replaces.
        heap = [(-c, p) for p, c in pairs.items()]
        heapq.heapify(heap)
        merges: list[tuple[int, int]] = []
        while len(merges) < n_merges and heap:
            negc, pair = heapq.heappop(heap)
            count = pairs.get(pair, 0)
            if count < 2:
                continue  # dead or noise-level pair (stale entry or < 2)
            if -negc != count:
                # Stale count: re-queue at the true value and keep popping.
                heapq.heappush(heap, (-count, pair))
                continue
            a, b = pair
            new_id = 256 + len(merges)
            merges.append(pair)
            changed: set[tuple[int, int]] = set()
            for wi in where.pop(pair, ()):
                sym, f = words[wi]
                for p in zip(sym, sym[1:]):
                    left = pairs.get(p, 0) - f
                    if left > 0:
                        pairs[p] = left
                    else:
                        pairs.pop(p, None)
                    ws = where.get(p)
                    if ws is not None:
                        ws.discard(wi)
                i = 0
                while i < len(sym) - 1:
                    if sym[i] == a and sym[i + 1] == b:
                        sym[i : i + 2] = [new_id]
                    else:
                        i += 1
                for p in zip(sym, sym[1:]):
                    pairs[p] = pairs.get(p, 0) + f
                    where.setdefault(p, set()).add(wi)
                    changed.add(p)
            for p in changed:
                if p in pairs:
                    heapq.heappush(heap, (-pairs[p], p))
        return cls(merges=merges, specials=specials)

    # -- encoding ------------------------------------------------------------
    def _bpe_word(self, word: bytes) -> list[int]:
        cached = self._cache.get(word)
        if cached is not None:
            return cached
        sym = list(word)
        while len(sym) > 1:
            # The lowest-rank (earliest-learned) pair present merges first.
            best = None
            best_rank = None
            for pair in zip(sym, sym[1:]):
                r = self._ranks.get(pair)
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = pair, r
            if best is None:
                break
            a, b = best
            new_id = 256 + best_rank
            i = 0
            while i < len(sym) - 1:
                if sym[i] == a and sym[i + 1] == b:
                    sym[i : i + 2] = [new_id]
                else:
                    i += 1
        if len(self._cache) < 1 << 16:
            self._cache[word] = sym
        return sym

    def encode(self, text: str) -> list[int]:
        if not self.specials:
            ids: list[int] = []
            for w in _pretokenize(text):
                ids.extend(self._bpe_word(w))
            return ids
        # Specials are whole-literal matches, longest first, before BPE.
        ids = []
        ordered = sorted(self.specials, key=len, reverse=True)
        rest = text
        while rest:
            # Earliest match wins; at equal positions the LONGEST special
            # wins (ordered is longest-first, so its index breaks the tie).
            hit = min(
                (
                    (rest.find(s), k, s)
                    for k, s in enumerate(ordered)
                    if s in rest
                ),
                default=None,
            )
            if hit is None:
                for w in _pretokenize(rest):
                    ids.extend(self._bpe_word(w))
                break
            pos, _, s = hit
            for w in _pretokenize(rest[:pos]):
                ids.extend(self._bpe_word(w))
            ids.append(self.special_id(s))
            rest = rest[pos + len(s):]
        return ids

    def decode(self, ids) -> str:
        out = bytearray()
        n_base = 256 + len(self.merges)
        # Expand merged ids depth-first back to bytes.
        stack = list(reversed([int(i) for i in ids]))
        while stack:
            i = stack.pop()
            if i < 256:
                out.append(i)
            elif i < n_base:
                a, b = self.merges[i - 256]
                stack.extend((b, a))
            else:
                out.extend(self.specials[i - n_base].encode("utf-8"))
        return out.decode("utf-8", errors="replace")

    def encode_corpus(self, texts) -> list[np.ndarray]:
        """Encode documents for `packing.pack_documents` — the
        text → packed-pretraining bridge."""
        return [np.asarray(self.encode(t), np.int32) for t in texts]

    # -- persistence ---------------------------------------------------------
    def save(self, path: str) -> str:
        payload = {
            "format": "hvt-bbpe-v1",
            "merges": [list(m) for m in self.merges],
            "specials": list(self.specials),
        }
        # One audited atomic-write implementation for the whole package
        # (unique temp per WRITE — see checkpoint._atomic_write).
        from horovod_tpu.checkpoint import _atomic_write

        _atomic_write(path, json.dumps(payload).encode())
        return path

    @classmethod
    def load(cls, path: str) -> "ByteBPETokenizer":
        def read_payload():
            with open(path) as f:
                return json.load(f)

        payload = stream_lib.read_with_retries(read_payload, path)
        if payload.get("format") != "hvt-bbpe-v1":
            raise ValueError(f"not a tokenizer file: {path}")
        return cls(
            merges=[tuple(m) for m in payload["merges"]],
            specials=payload["specials"],
        )
