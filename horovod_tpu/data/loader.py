"""Per-process sharded input pipeline with tf.data-style chaining.

Parity target: the reference's pipeline
``Dataset.from_tensor_slices(...).repeat().shuffle(10000).batch(128)``
(tensorflow2_keras_mnist.py:37-41). Same chainable verbs, plus the piece the
reference *lacks* (SURVEY.md §7.1 data.py note): ``shard()`` — the reference
feeds every rank the full dataset with independent shuffles; we split it by
process so each example is seen once per global epoch, while the
``shard_steps``/``shard_epochs`` helpers keep the reference's global-work
accounting (500//size, ceil(12/size)) intact.

Pure numpy on the host; device placement happens in the trainer via
`sharding.shard_batch`. Buffered shuffle reproduces tf.data's
``shuffle(buffer_size)`` semantics (stream through a k-slot reservoir)
rather than a full permutation, so the behavior matches at any scale.
"""

from __future__ import annotations

import os
from typing import Iterator, Sequence

import jax.tree_util
import numpy as np

from horovod_tpu.analysis import registry
from horovod_tpu.data import stream as stream_lib


class ArrayDataset:
    """An in-memory dataset of parallel arrays with chained transforms.

    ``arrays`` may be any pytree of same-leading-dim arrays — a plain
    ``(x, y)`` pair, or nested structures like ``({'src': ..., 'tgt': ...},
    y)`` for multi-input models (e.g. the seq2seq family): batches are
    yielded with the SAME structure, transforms operate on the flattened
    leaves."""

    def __init__(self, arrays):
        leaves, self._treedef = jax.tree_util.tree_flatten(arrays)
        arrays = tuple(np.asarray(a) for a in leaves)
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError("all arrays must share the leading dimension")
        self._arrays = arrays
        self._repeat = False
        self._shuffle_buffer = 0
        self._batch_size = None
        self._drop_remainder = True
        self._seed = 0
        # Elastic resharding support: `shard()` remembers the UNSHARDED
        # leaves and this view's (index, count) so `reshard()` can recut
        # the split at a new world size from the full data.
        self._unsharded = None
        self._shard_spec = None

    @classmethod
    def from_tensor_slices(cls, arrays) -> "ArrayDataset":
        return cls(arrays)

    @property
    def num_examples(self) -> int:
        return self._arrays[0].shape[0]

    @property
    def arrays(self) -> tuple:
        """The FLAT leaves (what the native batch-assembly engine consumes);
        pair with `structure` to rebuild full batches."""
        return self._arrays

    @property
    def structure(self):
        """The pytree structure batches are yielded with (a jax treedef)."""
        return self._treedef

    def shard(self, index: int, count: int) -> "ArrayDataset":
        """Keep every count-th example starting at index (per-process split).

        The pre-split arrays are retained so `reshard` can recut the same
        data at a different world size (the elastic rescale hook)."""
        if not (0 <= index < count):
            raise ValueError(f"shard index {index} out of range for count {count}")
        ds = self._clone()
        ds._unsharded = self._unsharded or self._arrays
        ds._arrays = tuple(a[index::count] for a in self._arrays)
        ds._shard_spec = (index, count)
        return ds

    @property
    def shard_spec(self) -> tuple[int, int] | None:
        """(index, count) of this view's split; None if unsharded."""
        return self._shard_spec

    def reshard(self, index: int, count: int) -> "ArrayDataset":
        """Recut the per-process split at a NEW world size from the
        ORIGINAL (unsharded) data — what the elastic rescale does to the
        input pipeline on a generation change (`horovod_tpu.elastic`).

        Unlike chaining ``.shard()`` on an already-sharded view (which
        splits the SPLIT — shards of shards), this re-derives shard
        ``index``/``count`` of the full dataset, so across the new world
        the shards again partition every example exactly once per epoch.
        Batch geometry (batch size, drop_remainder) carries over
        unchanged, keeping per-rank batch shapes static across a rescale
        — the dropped tail is at most ``batch_size - 1`` examples per
        shard, exactly as on the original sharding."""
        ds = self._clone()
        ds._arrays = self._unsharded or self._arrays
        ds._unsharded = None
        return ds.shard(index, count)

    def repeat(self) -> "ArrayDataset":
        ds = self._clone()
        ds._repeat = True
        return ds

    def shuffle(self, buffer_size: int, seed: int = 0) -> "ArrayDataset":
        ds = self._clone()
        ds._shuffle_buffer = int(buffer_size)
        ds._seed = seed
        return ds

    def batch(self, batch_size: int, drop_remainder: bool = True) -> "ArrayDataset":
        ds = self._clone()
        ds._batch_size = int(batch_size)
        ds._drop_remainder = drop_remainder
        return ds

    def _clone(self) -> "ArrayDataset":
        ds = ArrayDataset(self._arrays)
        ds._treedef = self._treedef
        ds._repeat = self._repeat
        ds._shuffle_buffer = self._shuffle_buffer
        ds._batch_size = self._batch_size
        ds._drop_remainder = self._drop_remainder
        ds._seed = self._seed
        ds._unsharded = self._unsharded
        ds._shard_spec = self._shard_spec
        return ds

    def _pass_indices(self, epoch: int, pass_: int = 0) -> Iterator[int]:
        """One shuffle pass over the examples, as a PURE function of
        ``(seed, epoch, pass_)`` (`stream.epoch_seed`): any epoch's order
        is regenerable without replaying the epochs before it — the
        positional-addressability invariant the durable stream cursors
        (`data.stream.StreamCursor`) are built on."""
        n = self.num_examples
        rng = np.random.RandomState(
            stream_lib.epoch_seed(self._seed, epoch, pass_)
        )
        order = np.arange(n)
        if self._shuffle_buffer >= n:
            # Buffer covers the dataset → full permutation (matches
            # tf.data when buffer_size >= dataset size).
            rng.shuffle(order)
            yield from order
        elif self._shuffle_buffer > 1:
            # Reservoir shuffle: identical semantics to tf.data's
            # bounded-buffer shuffle (restarted per pass, so each pass is
            # anchored — the reservoir never straddles epochs).
            buf = list(order[: self._shuffle_buffer])
            for idx in order[self._shuffle_buffer:]:
                j = rng.randint(0, len(buf))
                yield buf[j]
                buf[j] = idx
            while buf:
                j = rng.randint(0, len(buf))
                yield buf.pop(j)
        else:
            yield from order

    def __iter__(self):
        return self.batches()

    def _assemble(self, pending: list):
        sel = np.asarray(pending)
        return jax.tree_util.tree_unflatten(
            self._treedef, [a[sel] for a in self._arrays]
        )

    def batches(self, skip: int = 0, *, start_epoch: int = 0,
                batches_per_epoch: int | None = None):
        """Iterate batches, optionally fast-forwarded past the first
        ``skip`` batches WITHOUT materializing them: the skipped stretch
        only consumes integers from the shuffle's index stream (no row
        gathers, no batch assembly), so resuming a run at optimizer step S
        costs O(S·batch) index draws, not O(S·batch·row_bytes) of copied
        data.

        Every pass's order is a pure function of ``(seed, epoch, pass)``
        (`_pass_indices`), so positions are ADDRESSABLE: ``batches(
        start_epoch=E, skip=S)`` yields byte-identically what an
        uninterrupted stream would have yielded from that position —
        including when epochs [0, E) were consumed by an earlier process
        that no longer exists (the cross-epoch durable-cursor contract;
        `reshard` at the same world size preserves it — identical arrays
        → identical stream).

        ``batches_per_epoch=None`` (default): one shuffle pass IS an
        epoch; the batch remainder of a pass straddles into the next in
        repeat mode (the historical tf.data-chain contract), so
        cross-epoch positions are exact when ``batch_size`` divides the
        example count.

        ``batches_per_epoch=B``: trainer-anchored epochs — epoch ``e``
        yields EXACTLY ``B`` batches drawn from passes ``(e, 0), (e, 1),
        ...`` (a new pass starts within the epoch when one is exhausted;
        partial batches carry across passes but are DISCARDED at the
        epoch boundary), then the stream advances to epoch ``e+1``
        regardless of ``repeat()``. This is the mode `Trainer.fit`'s
        streamed path drives: epoch boundaries are clean cuts, so a
        cursor ``(epoch, step)`` is exact for ANY batch size."""
        if self._batch_size is None:
            raise ValueError("call .batch(batch_size) before iterating")
        bs = self._batch_size
        skip = int(skip)
        skipped = 0
        if batches_per_epoch is None:
            pending: list[int] = []
            epoch = int(start_epoch)
            while True:
                for idx in self._pass_indices(epoch):
                    pending.append(idx)
                    if len(pending) == bs:
                        if skipped < skip:
                            skipped += 1
                            pending = []
                            continue
                        out = self._assemble(pending)
                        pending = []
                        yield out
                epoch += 1
                if not self._repeat:
                    break
            if pending and not self._drop_remainder:
                if skipped < skip:
                    return
                yield self._assemble(pending)
            return
        B = int(batches_per_epoch)
        if B < 1:
            raise ValueError(f"batches_per_epoch must be >= 1, got {B}")
        epoch = int(start_epoch)
        while True:
            emitted = 0
            pass_ = 0
            pending = []
            while emitted < B:
                for idx in self._pass_indices(epoch, pass_):
                    pending.append(idx)
                    if len(pending) == bs:
                        emitted += 1
                        if skipped < skip:
                            skipped += 1
                            pending = []
                        else:
                            out = self._assemble(pending)
                            pending = []
                            yield out
                        if emitted >= B:
                            break
                else:
                    # Pass exhausted mid-epoch: continue with the next
                    # anchored pass of the SAME epoch (pending carries).
                    pass_ += 1
                    continue
                break
            epoch += 1

    # --- durable stream cursors (data.stream) -------------------------------

    def stream_cursor(self, epoch: int, step: int,
                      batches_per_epoch: int | None = None
                      ) -> "stream_lib.StreamCursor":
        """Export the position "``step`` batches into epoch ``epoch``" as
        a serializable `StreamCursor` — `batches_from` reconstructs the
        stream from it byte-exactly (same geometry required)."""
        if self._batch_size is None:
            raise ValueError("call .batch(batch_size) before cursor export")
        return stream_lib.StreamCursor(
            kind="array", seed=int(self._seed), epoch=int(epoch),
            step=int(step),
            position={
                "n_examples": self.num_examples,
                "batch_size": self._batch_size,
                "shard": list(self._shard_spec) if self._shard_spec else None,
                "shuffle_buffer": self._shuffle_buffer,
                "batches_per_epoch": batches_per_epoch,
            },
        )

    def batches_from(self, cursor):
        """Reconstruct the batch stream from a `StreamCursor` (or its
        dict form): validates format/kind/seed/geometry loudly
        (`stream.StreamCursorError`), then yields byte-identically what
        the exporting stream would have yielded from that position on."""
        if not isinstance(cursor, stream_lib.StreamCursor):
            cursor = stream_lib.StreamCursor.from_dict(cursor)
        cursor.require(
            "array", seed=self._seed,
            n_examples=self.num_examples,
            batch_size=self._batch_size,
            shard=list(self._shard_spec) if self._shard_spec else None,
            shuffle_buffer=self._shuffle_buffer,
        )
        return self.batches(
            skip=cursor.step, start_epoch=cursor.epoch,
            batches_per_epoch=cursor.position.get("batches_per_epoch"),
        )

    def take(self, n_batches: int):
        it = iter(self)
        return [next(it) for _ in range(n_batches)]


def training_pipeline(
    arrays: Sequence[np.ndarray],
    batch_size: int,
    seed: int = 0,
    shuffle_buffer: int | None = None,
    structure=None,
    skip_batches: int = 0,
    start_epoch: int = 0,
    batches_per_epoch: int | None = None,
    engine_out: dict | None = None,
):
    """The training-path input iterator: infinite shuffled batches of the
    given arrays (the reference's ``repeat().shuffle().batch()`` chain,
    tensorflow2_keras_mnist.py:37-41).

    Routes to the native batch-assembly engine
    (`horovod_tpu.data.native_loader`, the framework's C++ runtime slot —
    SURVEY.md §2.3) when it is available and the requested shuffle covers the
    whole dataset (a full per-epoch permutation, which is also what the
    reference's shuffle(10000)-over-60k effectively does); falls back to the
    pure-Python `ArrayDataset` chain otherwise — including under
    ``HVT_NO_NATIVE=1`` or without a C++ toolchain.

    Returns ``(iterator, close)``: call ``close()`` when done so the native
    producer thread and its staging ring are torn down promptly rather than
    at GC time.

    ``arrays`` are FLAT leaves (what the native engine consumes); pass
    ``structure`` (an `ArrayDataset.structure` treedef) to have batches
    rebuilt into the original pytree shape — how dict-input (multi-input)
    models ride both the native and Python assembly paths.

    ``skip_batches`` fast-forwards the stream past its first N batches —
    the step-granular resume hook (`Trainer.fit(initial_step=)`). Each
    engine skips within ITS OWN deterministic stream (python: index draws
    only, nothing materialized; native: slots advanced and released
    without a host copy), so a resumed run sees byte-identically the
    batches an uninterrupted run of the same engine would have seen from
    that position.

    ``start_epoch``/``batches_per_epoch`` anchor the stream's epochs to
    ABSOLUTE epoch numbers (per-engine pure ``(seed, epoch, pass)``
    derivations): the stream starts at epoch ``start_epoch`` — including
    when epochs before it were consumed by a process that no longer
    exists — and, with ``batches_per_epoch=B``, each epoch is exactly B
    batches (the `Trainer.fit` streamed contract; see
    `ArrayDataset.batches`). Together with ``skip_batches`` this is the
    durable-cursor reconstruction hook: ``(start_epoch=E, skip=S)`` is
    cursor position ``(E, S)``.

    ``engine_out`` (a dict, filled in place) reports which engine was
    selected (``{'engine': 'native' | 'python'}``): the two engines'
    anchored streams are DIFFERENT byte streams, so durable cursors must
    record which one produced them — a resume that lands on the other
    engine (toolchain missing, ``HVT_NO_NATIVE`` flipped) is then
    detectable instead of silently re-anchored.
    """
    skip_batches = int(skip_batches)

    def rebuild(it):
        if structure is None:
            return it
        return (
            jax.tree_util.tree_unflatten(structure, list(b)) for b in it
        )

    n = len(arrays[0])
    full_shuffle = shuffle_buffer is None or shuffle_buffer >= n
    if full_shuffle and not registry.get_flag("HVT_NO_NATIVE"):
        from horovod_tpu.data import native_loader

        if native_loader.available() and batch_size <= n:
            loader = native_loader.NativeBatchLoader(
                arrays, batch_size, seed=seed, shuffle=True,
                start_epoch=start_epoch,
                batches_per_epoch=batches_per_epoch or 0,
            )
            if skip_batches:
                loader.skip(skip_batches)
            if engine_out is not None:
                engine_out["engine"] = "native"
            return rebuild(iter(loader)), loader.close
    if engine_out is not None:
        engine_out["engine"] = "python"
    ds = (
        ArrayDataset(arrays)
        .repeat()
        .shuffle(shuffle_buffer or n, seed=seed)
        .batch(batch_size)
    )
    return rebuild(
        ds.batches(
            skip=skip_batches, start_epoch=start_epoch,
            batches_per_epoch=batches_per_epoch,
        )
    ), lambda: None
