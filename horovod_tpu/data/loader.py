"""Per-process sharded input pipeline with tf.data-style chaining.

Parity target: the reference's pipeline
``Dataset.from_tensor_slices(...).repeat().shuffle(10000).batch(128)``
(tensorflow2_keras_mnist.py:37-41). Same chainable verbs, plus the piece the
reference *lacks* (SURVEY.md §7.1 data.py note): ``shard()`` — the reference
feeds every rank the full dataset with independent shuffles; we split it by
process so each example is seen once per global epoch, while the
``shard_steps``/``shard_epochs`` helpers keep the reference's global-work
accounting (500//size, ceil(12/size)) intact.

Pure numpy on the host; device placement happens in the trainer via
`sharding.shard_batch`. Buffered shuffle reproduces tf.data's
``shuffle(buffer_size)`` semantics (stream through a k-slot reservoir)
rather than a full permutation, so the behavior matches at any scale.
"""

from __future__ import annotations

import os
from typing import Iterator, Sequence

import jax.tree_util
import numpy as np

from horovod_tpu.analysis import registry


class ArrayDataset:
    """An in-memory dataset of parallel arrays with chained transforms.

    ``arrays`` may be any pytree of same-leading-dim arrays — a plain
    ``(x, y)`` pair, or nested structures like ``({'src': ..., 'tgt': ...},
    y)`` for multi-input models (e.g. the seq2seq family): batches are
    yielded with the SAME structure, transforms operate on the flattened
    leaves."""

    def __init__(self, arrays):
        leaves, self._treedef = jax.tree_util.tree_flatten(arrays)
        arrays = tuple(np.asarray(a) for a in leaves)
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError("all arrays must share the leading dimension")
        self._arrays = arrays
        self._repeat = False
        self._shuffle_buffer = 0
        self._batch_size = None
        self._drop_remainder = True
        self._seed = 0
        # Elastic resharding support: `shard()` remembers the UNSHARDED
        # leaves and this view's (index, count) so `reshard()` can recut
        # the split at a new world size from the full data.
        self._unsharded = None
        self._shard_spec = None

    @classmethod
    def from_tensor_slices(cls, arrays) -> "ArrayDataset":
        return cls(arrays)

    @property
    def num_examples(self) -> int:
        return self._arrays[0].shape[0]

    @property
    def arrays(self) -> tuple:
        """The FLAT leaves (what the native batch-assembly engine consumes);
        pair with `structure` to rebuild full batches."""
        return self._arrays

    @property
    def structure(self):
        """The pytree structure batches are yielded with (a jax treedef)."""
        return self._treedef

    def shard(self, index: int, count: int) -> "ArrayDataset":
        """Keep every count-th example starting at index (per-process split).

        The pre-split arrays are retained so `reshard` can recut the same
        data at a different world size (the elastic rescale hook)."""
        if not (0 <= index < count):
            raise ValueError(f"shard index {index} out of range for count {count}")
        ds = self._clone()
        ds._unsharded = self._unsharded or self._arrays
        ds._arrays = tuple(a[index::count] for a in self._arrays)
        ds._shard_spec = (index, count)
        return ds

    @property
    def shard_spec(self) -> tuple[int, int] | None:
        """(index, count) of this view's split; None if unsharded."""
        return self._shard_spec

    def reshard(self, index: int, count: int) -> "ArrayDataset":
        """Recut the per-process split at a NEW world size from the
        ORIGINAL (unsharded) data — what the elastic rescale does to the
        input pipeline on a generation change (`horovod_tpu.elastic`).

        Unlike chaining ``.shard()`` on an already-sharded view (which
        splits the SPLIT — shards of shards), this re-derives shard
        ``index``/``count`` of the full dataset, so across the new world
        the shards again partition every example exactly once per epoch.
        Batch geometry (batch size, drop_remainder) carries over
        unchanged, keeping per-rank batch shapes static across a rescale
        — the dropped tail is at most ``batch_size - 1`` examples per
        shard, exactly as on the original sharding."""
        ds = self._clone()
        ds._arrays = self._unsharded or self._arrays
        ds._unsharded = None
        return ds.shard(index, count)

    def repeat(self) -> "ArrayDataset":
        ds = self._clone()
        ds._repeat = True
        return ds

    def shuffle(self, buffer_size: int, seed: int = 0) -> "ArrayDataset":
        ds = self._clone()
        ds._shuffle_buffer = int(buffer_size)
        ds._seed = seed
        return ds

    def batch(self, batch_size: int, drop_remainder: bool = True) -> "ArrayDataset":
        ds = self._clone()
        ds._batch_size = int(batch_size)
        ds._drop_remainder = drop_remainder
        return ds

    def _clone(self) -> "ArrayDataset":
        ds = ArrayDataset(self._arrays)
        ds._treedef = self._treedef
        ds._repeat = self._repeat
        ds._shuffle_buffer = self._shuffle_buffer
        ds._batch_size = self._batch_size
        ds._drop_remainder = self._drop_remainder
        ds._seed = self._seed
        ds._unsharded = self._unsharded
        ds._shard_spec = self._shard_spec
        return ds

    def _index_stream(self) -> Iterator[int]:
        n = self.num_examples
        rng = np.random.RandomState(self._seed)
        epoch = 0
        while True:
            order = np.arange(n)
            if self._shuffle_buffer >= n:
                # Buffer covers the dataset → full permutation (matches
                # tf.data when buffer_size >= dataset size).
                rng.shuffle(order)
                yield from order
            elif self._shuffle_buffer > 1:
                # Reservoir shuffle: identical semantics to tf.data's
                # bounded-buffer shuffle.
                buf = list(order[: self._shuffle_buffer])
                for idx in order[self._shuffle_buffer:]:
                    j = rng.randint(0, len(buf))
                    yield buf[j]
                    buf[j] = idx
                while buf:
                    j = rng.randint(0, len(buf))
                    yield buf.pop(j)
            else:
                yield from order
            epoch += 1
            if not self._repeat:
                return

    def __iter__(self):
        return self.batches()

    def batches(self, skip: int = 0):
        """Iterate batches, optionally fast-forwarded past the first
        ``skip`` batches WITHOUT materializing them: the skipped stretch
        only consumes integers from the shuffle's index stream (no row
        gathers, no batch assembly), so resuming a run at optimizer step S
        costs O(S·batch) index draws, not O(S·batch·row_bytes) of copied
        data. The stream is a pure function of (seed, shard geometry), so
        ``ds.batches(skip=n)`` yields byte-identically what the (n+1)-th
        ``iter(ds)`` batch onward would — the deterministic-resume
        contract `Trainer.fit(initial_step=)` builds on; `reshard` at the
        same world size preserves it (identical arrays → identical
        stream)."""
        if self._batch_size is None:
            raise ValueError("call .batch(batch_size) before iterating")
        bs = self._batch_size
        skipped = 0
        pending: list[int] = []
        unflatten = jax.tree_util.tree_unflatten
        for idx in self._index_stream():
            pending.append(idx)
            if len(pending) == bs:
                if skipped < skip:
                    skipped += 1
                    pending = []
                    continue
                sel = np.asarray(pending)
                pending = []
                yield unflatten(self._treedef, [a[sel] for a in self._arrays])
        if pending and not self._drop_remainder:
            if skipped < skip:
                return
            sel = np.asarray(pending)
            yield unflatten(self._treedef, [a[sel] for a in self._arrays])

    def take(self, n_batches: int):
        it = iter(self)
        return [next(it) for _ in range(n_batches)]


def training_pipeline(
    arrays: Sequence[np.ndarray],
    batch_size: int,
    seed: int = 0,
    shuffle_buffer: int | None = None,
    structure=None,
    skip_batches: int = 0,
):
    """The training-path input iterator: infinite shuffled batches of the
    given arrays (the reference's ``repeat().shuffle().batch()`` chain,
    tensorflow2_keras_mnist.py:37-41).

    Routes to the native batch-assembly engine
    (`horovod_tpu.data.native_loader`, the framework's C++ runtime slot —
    SURVEY.md §2.3) when it is available and the requested shuffle covers the
    whole dataset (a full per-epoch permutation, which is also what the
    reference's shuffle(10000)-over-60k effectively does); falls back to the
    pure-Python `ArrayDataset` chain otherwise — including under
    ``HVT_NO_NATIVE=1`` or without a C++ toolchain.

    Returns ``(iterator, close)``: call ``close()`` when done so the native
    producer thread and its staging ring are torn down promptly rather than
    at GC time.

    ``arrays`` are FLAT leaves (what the native engine consumes); pass
    ``structure`` (an `ArrayDataset.structure` treedef) to have batches
    rebuilt into the original pytree shape — how dict-input (multi-input)
    models ride both the native and Python assembly paths.

    ``skip_batches`` fast-forwards the stream past its first N batches —
    the step-granular resume hook (`Trainer.fit(initial_step=)`). Each
    engine skips within ITS OWN deterministic stream (python: index draws
    only, nothing materialized; native: slots advanced and released
    without a host copy), so a resumed run sees byte-identically the
    batches an uninterrupted run of the same engine would have seen from
    that position.
    """
    skip_batches = int(skip_batches)

    def rebuild(it):
        if structure is None:
            return it
        return (
            jax.tree_util.tree_unflatten(structure, list(b)) for b in it
        )

    n = len(arrays[0])
    full_shuffle = shuffle_buffer is None or shuffle_buffer >= n
    if full_shuffle and not registry.get_flag("HVT_NO_NATIVE"):
        from horovod_tpu.data import native_loader

        if native_loader.available() and batch_size <= n:
            loader = native_loader.NativeBatchLoader(
                arrays, batch_size, seed=seed, shuffle=True
            )
            if skip_batches:
                loader.skip(skip_batches)
            return rebuild(iter(loader)), loader.close
    ds = (
        ArrayDataset(arrays)
        .repeat()
        .shuffle(shuffle_buffer or n, seed=seed)
        .batch(batch_size)
    )
    return rebuild(ds.batches(skip=skip_batches)), lambda: None
