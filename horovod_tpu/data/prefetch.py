"""Background host→device prefetch.

`jax.device_put` blocks the calling thread for the RPC enqueue (sub-ms on a
local PCIe host, ~1 ms per call over a networked TPU tunnel) even though the
transfer itself is asynchronous — so a training loop that stages its own
batches serializes transfer enqueue with step dispatch. A `DevicePrefetcher`
moves the staging onto a daemon thread feeding a small queue of
already-device-resident batches: while step k computes, batch k+1 is being
transferred. This is the framework's equivalent of the input-side overlap the
reference gets from tf.data's prefetch + Horovod's background threads.

Composes with the native batch-assembly engine (`native_loader`): the host
iterator it wraps may itself be the C++ producer, giving a two-stage
pipeline: C++ assembles batch bytes → this thread stages them on device →
the main thread only dispatches compiled steps.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator


class DevicePrefetcher:
    """Iterate device-resident items staged ahead by a background thread.

    Args:
      host_iter: yields host-side items (e.g. numpy batch tuples).
      put: host item -> device item (e.g. `trainer._shard`); runs on the
        background thread.
      depth: max staged items. 2 = classic double buffering; more only helps
        when production is bursty.

    Exceptions raised by `host_iter` or `put` re-raise in the consumer at the
    matching `__next__` call. Always `close()` (or exhaust) so the thread and
    its staged device buffers are released promptly.
    """

    _DONE = object()

    def __init__(self, host_iter: Iterator, put: Callable, depth: int = 2):
        self._q: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._produce, args=(host_iter, put), daemon=True
        )
        self._thread.start()

    def _enqueue(self, item) -> None:
        # Blocking put with a timeout so close() can't strand the producer
        # on a full queue nobody will ever drain.
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def _produce(self, host_iter, put):
        try:
            for item in host_iter:
                if self._stop.is_set():
                    return
                self._enqueue(put(item))
            self._enqueue(self._DONE)
        except BaseException as e:  # noqa: BLE001 — delivered to consumer
            self._enqueue(e)
            # Then terminate the stream: a consumer that catches the error
            # and calls next() again must get StopIteration, not a hang.
            self._enqueue(self._DONE)

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._DONE:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self) -> None:
        self._stop.set()
        # Drain so a blocked producer can observe the stop flag.
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5)
