"""hvt-data — the distributed data service dispatcher (ROADMAP item 6).

Every feeding engine in this repo is rank-local: N fleet jobs re-read
and re-shuffle the same corpora, and a data-side fault is invisible to
the supervisor. This daemon centralizes feeding WITHOUT centralizing
failure: the dispatcher owns ``(seed, epoch, pass)`` order per job and
streams packed batches to ranks over a length-prefixed socket protocol,
but — because PR 8 made batch order a PURE function of position
(`data.stream.epoch_seed`) — it holds no state a client cannot
reconstruct. Three consequences the whole design leans on:

* **Crash-recoverable.** Admissions (job, shard, source spec) are
  journaled to ``<dir>/data-journal.jsonl`` as they happen; a SIGKILLed
  dispatcher restarts with the same ``--dir`` and adopts every in-flight
  job from the journal plus the cursors its re-attaching clients
  present. No handshake state survives the crash and none is needed.
* **Split-brain-free.** Any dispatcher instance can serve any batch by
  POSITION (the client's `StreamCursor`), never by connection state: two
  dispatchers serving the same job from the same spec produce the same
  bytes, so a failover can never fork the stream.
* **Gracefully degradable.** The trainer-side client
  (`data.client.ServiceClient`) falls back to rank-local feeding *from
  the same cursor* when its retry budget is exhausted — byte-identically,
  because both sides derive the stream from the same ``(seed, epoch,
  pass)`` derivation via `build_source`.

Per-job isolation: every job carries its own lock; the dispatcher-wide
lock guards only dict lookups, and each connection is served by its own
thread (`ThreadingTCPServer`) — a wedged or backlogged job blocks its
own queue, never another job's admission or serving path.

Wire protocol (version `PROTOCOL_VERSION`): each frame is a fixed
``!II`` prefix (header length, payload length), a JSON header, then raw
payload bytes. Ops:

* ``hello`` — register/adopt ``(job, shard)``. A first attach carries
  ``spec`` (the `build_source` recipe); a RE-attach carries none — the
  dispatcher must already know the job (its own memory or the journal),
  which is exactly what makes a successful spec-less re-attach the proof
  of journal recovery. An optional ``cursor`` is validated loudly.
* ``next`` — serve the batch at ``cursor``. The response header carries
  per-leaf dtype/shape; the payload is the concatenated contiguous
  bytes of the batch's flattened leaves.
* ``ping`` — liveness + admitted-job census.

`StreamCursor` refusals (foreign format version, wrong engine kind,
mismatched geometry) survive serialization: they come back as
``{"ok": false, "refusal": true}`` and the client re-raises
`StreamCursorError` — never retried, never silently re-anchored.

Observability: a private `obs.core.Registry` serves ``hvt_data_*``
series on ``GET /metrics`` (``--metrics-port``), reusing
`obs.server.start_metrics_server`. ``hvt_data_cursor_refusals_total`` is
pre-seeded to 0 at startup so a fleet gate of ``0..0`` can distinguish
"no refusals" from "series absent".

CLI: ``hvt-data serve --dir DIR [--port P] [--metrics-port M]`` (also
``python -m horovod_tpu.data.service``).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import socketserver
import struct
import sys
import threading
import time

import numpy as np

from horovod_tpu.analysis import registry
from horovod_tpu.data import stream as stream_lib
from horovod_tpu.obs import core as obs_core

PROTOCOL_VERSION = 1
JOURNAL_NAME = "data-journal.jsonl"

_FRAME = struct.Struct("!II")


# --- wire protocol -----------------------------------------------------------


def _recv_exact(sock: socket.socket, n: int, *, mid_frame: bool) -> bytes | None:
    """Read exactly ``n`` bytes. Clean EOF at a frame boundary returns
    None; EOF mid-frame is a torn frame and raises (retriable for the
    client — the connection died, the position did not)."""
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if buf or mid_frame:
                raise ConnectionError(
                    "connection closed mid-frame (torn hvt-data frame)"
                )
            return None
        buf += chunk
    return buf


def send_frame(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    data = json.dumps(header).encode()
    sock.sendall(_FRAME.pack(len(data), len(payload)) + data + payload)


def recv_frame(sock: socket.socket) -> tuple[dict | None, bytes]:
    """One frame off the socket: ``(header, payload)``, or ``(None, b"")``
    on clean EOF."""
    head = _recv_exact(sock, _FRAME.size, mid_frame=False)
    if head is None:
        return None, b""
    hlen, plen = _FRAME.unpack(head)
    header = json.loads(_recv_exact(sock, hlen, mid_frame=True))
    payload = _recv_exact(sock, plen, mid_frame=True) if plen else b""
    return header, payload


# --- the shared source recipe ------------------------------------------------


def build_source(spec: dict):
    """Construct the batch source a spec describes — the SAME function on
    the dispatcher and in every client, so a degraded client feeding
    itself rank-locally produces byte-identically what the service would
    have served (both are the pure ``(seed, epoch, pass)`` stream of an
    identical `ArrayDataset` chain).

    Spec fields: ``source`` ("npz"), ``path``, ``keys`` (npz member
    names, in batch-leaf order; default: the archive's own order),
    ``batch_size``, ``seed``, ``shuffle_buffer`` (falsy → full
    permutation), ``shard`` ([index, count] or null)."""
    from horovod_tpu.data import loader

    kind = spec.get("source", "npz")
    if kind != "npz":
        raise ValueError(
            f"unknown data-service source kind {kind!r} (only 'npz' specs "
            "are servable today)"
        )
    path = spec["path"]
    keys = list(spec.get("keys") or [])

    def load_npz():
        with np.load(path) as f:
            names = keys or list(f.files)
            return tuple(np.asarray(f[k]) for k in names)

    arrays = stream_lib.read_with_retries(load_npz, f"corpus npz {path}")
    ds = loader.ArrayDataset(arrays)
    shard = spec.get("shard")
    if shard:
        ds = ds.shard(int(shard[0]), int(shard[1]))
    ds = ds.repeat()
    buf = spec.get("shuffle_buffer")
    ds = ds.shuffle(int(buf) if buf else ds.num_examples,
                    seed=int(spec.get("seed", 0)))
    return ds.batch(int(spec["batch_size"]))


def _shard_key(shard) -> str:
    if not shard:
        return "0/1"
    return f"{int(shard[0])}/{int(shard[1])}"


# --- the dispatcher ----------------------------------------------------------


class DataService:
    """One dispatcher instance: admitted jobs, their per-shard stream
    state, the admission journal, and the metrics registry. `start()`
    binds and serves on background threads (in-process tests drive it
    directly); the CLI wraps it in a foreground daemon."""

    def __init__(self, root_dir: str, host: str | None = None,
                 port: int = 0, metrics_port: int | None = None):
        self.root_dir = root_dir
        self.host = host if host is not None else (
            registry.get_str("HVT_STATUS_HOST") or "127.0.0.1"
        )
        self.port = port
        self.metrics_port = metrics_port
        self.journal_path = os.path.join(root_dir, JOURNAL_NAME)
        self.registry = obs_core.Registry()
        self._lock = threading.Lock()        # the job MAP only — never
        self._journal_lock = threading.Lock()  # held across stream work
        # job -> {"lock": RLock, "shards": {shard_key: {"spec", "src",
        #         "it", "pos"}}}
        self._jobs: dict[str, dict] = {}
        self._server = None
        self._metrics_server = None
        self._conns: set = set()  # live client sockets, severed on stop()
        os.makedirs(root_dir, exist_ok=True)
        # Pre-seed the refusal series: the fleet gate asserts 0..0, and
        # an ABSENT series fails `ci_gate.run_prom_checks` by design.
        self.registry.counter("hvt_data_cursor_refusals_total", 0)
        self._recover()

    # -- admission / recovery -------------------------------------------------

    def _journal(self, record: dict) -> None:
        record = dict(record, wall_time=time.time())
        with self._journal_lock:
            with open(self.journal_path, "a") as f:
                f.write(json.dumps(record) + "\n")
                f.flush()

    def _recover(self) -> None:
        """Adopt every job the journal admitted: the SIGKILL-survival
        path. Sources are rebuilt lazily at first request — a dispatcher
        can adopt a hundred jobs without loading a hundred corpora."""

        def read_journal():
            if not os.path.exists(self.journal_path):
                return []
            with open(self.journal_path) as f:
                return f.readlines()

        lines = stream_lib.read_with_retries(
            read_journal, f"data-service journal {self.journal_path}"
        )
        adopted = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail line from the crash — admissions
                # before it are intact (append-only discipline)
            if rec.get("name") != "admit":
                continue
            job, sk = str(rec.get("job")), str(rec.get("shard_key"))
            entry = self._job_entry(job)
            entry["shards"][sk] = {
                "spec": rec.get("spec"), "src": None, "it": None,
                "pos": None,
            }
            adopted += 1
        if adopted:
            self._journal({"name": "recover", "value": float(adopted)})
        for job in self._jobs:
            self.registry.counter("hvt_data_admissions_total", 0, job=job)
            self.registry.counter(
                "hvt_data_batches_served_total", 0, job=job
            )
        self.registry.gauge("hvt_data_jobs", len(self._jobs))

    def _job_entry(self, job: str) -> dict:
        with self._lock:
            entry = self._jobs.get(job)
            if entry is None:
                entry = self._jobs[job] = {
                    "lock": threading.RLock(), "shards": {},
                }
            return entry

    def admit(self, job: str, shard, spec: dict) -> None:
        """Register ``spec`` as the source recipe for ``(job, shard)``
        and journal it — the durable admission a restarted dispatcher
        adopts."""
        sk = _shard_key(shard)
        entry = self._job_entry(job)
        with entry["lock"]:
            entry["shards"][sk] = {
                "spec": dict(spec), "src": None, "it": None, "pos": None,
            }
        self._journal({
            "name": "admit", "value": 1.0, "job": job, "shard_key": sk,
            "spec": dict(spec),
        })
        self.registry.counter("hvt_data_admissions_total", job=job)
        self.registry.counter("hvt_data_batches_served_total", 0, job=job)
        with self._lock:
            n_jobs = len(self._jobs)
        self.registry.gauge("hvt_data_jobs", n_jobs)

    def register_local(self, job: str, shard, source) -> None:
        """Test hook: admit a pre-built in-memory source (no spec, no
        journal durability) — how the isolation unit wedges one job's
        stream without touching the filesystem."""
        sk = _shard_key(shard)
        entry = self._job_entry(job)
        with entry["lock"]:
            entry["shards"][sk] = {
                "spec": None, "src": source, "it": None, "pos": None,
            }
        self.registry.counter("hvt_data_admissions_total", job=job)
        self.registry.counter("hvt_data_batches_served_total", 0, job=job)
        self.registry.gauge("hvt_data_jobs", len(self._jobs))

    # -- serving --------------------------------------------------------------

    def _shard_state(self, job: str, shard) -> tuple[dict, dict]:
        """(job entry, shard state) or a loud KeyError naming what is
        unknown — the client treats it as transient (the dispatcher may
        be a fresh instance that has not seen this job's admission) and
        stays on its local fallback."""
        sk = _shard_key(shard)
        with self._lock:
            entry = self._jobs.get(job)
        if entry is None:
            raise KeyError(
                f"unknown job {job!r} — not admitted to this dispatcher "
                "and absent from its journal"
            )
        with entry["lock"]:
            sh = entry["shards"].get(sk)
        if sh is None:
            raise KeyError(
                f"job {job!r} has no admission for shard {sk} on this "
                "dispatcher"
            )
        return entry, sh

    @staticmethod
    def _source_of(sh: dict):
        if sh["src"] is None:
            sh["src"] = build_source(sh["spec"])
        return sh["src"]

    def _validate_cursor(self, job: str, shard, cursor_dict: dict) -> None:
        """Loud `StreamCursorError` when a presented cursor cannot be
        honoured byte-exactly by this (job, shard)'s source — the PR 8
        refusal semantics, surviving serialization."""
        entry, sh = self._shard_state(job, shard)
        with entry["lock"]:
            src = self._source_of(sh)
            # `batches_from` validates format/kind/seed/geometry EAGERLY
            # (the generator it returns is lazy, the require() is not) —
            # building and discarding it is exactly the validation.
            src.batches_from(cursor_dict)

    def _next_batch(self, job: str, shard, cursor_dict: dict):
        """The batch at ``cursor`` — by POSITION. The per-shard iterator
        is a cache: when the requested position is exactly where the
        cached iterator stands, serving is one `next()`; any other
        position (client retry, re-attach after OUR crash, a rewound
        cursor) rebuilds the stream from the cursor — same bytes either
        way, which is the whole failover argument."""
        entry, sh = self._shard_state(job, shard)
        with entry["lock"]:
            src = self._source_of(sh)
            cursor = stream_lib.StreamCursor.from_dict(cursor_dict)
            pos = (cursor.epoch, cursor.step)
            if sh["it"] is None or sh["pos"] != pos:
                sh["it"] = src.batches_from(cursor)
            batch = next(sh["it"])
            b_per_epoch = cursor.position.get("batches_per_epoch")
            epoch, step = pos[0], pos[1] + 1
            if b_per_epoch and step >= int(b_per_epoch):
                epoch, step = epoch + 1, 0
            sh["pos"] = (epoch, step)
        self.registry.counter("hvt_data_batches_served_total", job=job)
        return batch

    # -- the socket server ----------------------------------------------------

    def _handle_request(self, req: dict) -> tuple[dict, bytes]:
        op = req.get("op")
        job = str(req.get("job") or "default")
        shard = req.get("shard")
        if op == "ping":
            with self._lock:
                jobs = {
                    j: sorted(e["shards"]) for j, e in self._jobs.items()
                }
            return {"ok": True, "protocol": PROTOCOL_VERSION,
                    "jobs": jobs}, b""
        if op == "hello":
            spec = req.get("spec")
            if spec is not None:
                self.admit(job, shard, spec)
            else:
                self._shard_state(job, shard)  # must already be admitted
            if req.get("cursor") is not None:
                self._validate_cursor(job, shard, req["cursor"])
            return {"ok": True, "job": job,
                    "adopted": spec is None}, b""
        if op == "next":
            cursor = req["cursor"]
            ms = _dataslow_ms(int(cursor.get("epoch", 0)), shard)
            if ms is not None:
                time.sleep(ms / 1e3)
            batch = self._next_batch(job, shard, cursor)
            import jax.tree_util

            leaves = [
                np.ascontiguousarray(a)
                for a in jax.tree_util.tree_leaves(batch)
            ]
            payload = b"".join(a.tobytes() for a in leaves)
            return {
                "ok": True,
                "leaves": [
                    {"dtype": str(a.dtype), "shape": list(a.shape)}
                    for a in leaves
                ],
            }, payload
        return {"ok": False, "error": f"unknown op {op!r}"}, b""

    def start(self):
        """Bind and serve on background threads; returns self. The bound
        port lands in ``self.port`` (``port=0`` binds ephemerally)."""
        service = self

        class Handler(socketserver.BaseRequestHandler):
            def setup(self):
                with service._lock:
                    service._conns.add(self.request)

            def finish(self):
                with service._lock:
                    service._conns.discard(self.request)

            def handle(self):
                while True:
                    try:
                        req, _ = recv_frame(self.request)
                    except (OSError, ValueError):
                        return  # torn/garbled frame: drop the connection
                    if req is None:
                        return
                    try:
                        header, payload = service._handle_request(req)
                    except stream_lib.StreamCursorError as e:
                        service.registry.counter(
                            "hvt_data_cursor_refusals_total"
                        )
                        header, payload = {
                            "ok": False, "refusal": True, "error": str(e),
                        }, b""
                    except Exception as e:
                        header, payload = {
                            "ok": False,
                            "error": f"{type(e).__name__}: {e}",
                        }, b""
                    try:
                        send_frame(self.request, header, payload)
                    except OSError:
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((self.host, self.port), Handler)
        self.port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True
        ).start()
        if self.metrics_port is not None:
            from horovod_tpu.obs import server as obs_server

            self._metrics_server = obs_server.start_metrics_server(
                self.metrics_port, host=self.host, registry=self.registry
            )
            self.metrics_port = self._metrics_server.server_address[1]
        self._journal({
            "name": "serve_start", "value": 1.0, "port": self.port,
            "metrics_port": self.metrics_port, "pid": os.getpid(),
        })
        return self

    def stop(self) -> None:
        """Tear down like a crash would: the listener AND every live
        connection die (in-process tests rely on stop() being
        indistinguishable from a SIGKILL at the socket layer)."""
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for sock in conns:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._metrics_server is not None:
            self._metrics_server.shutdown()
            self._metrics_server.server_close()
            self._metrics_server = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


def _dataslow_ms(epoch: int, shard) -> float | None:
    """The ``dataslow:MS`` fault's per-response delay applying to this
    request, or None (`testing.faults.data_fault_ms`; the fault's rank is
    matched against the requesting client's shard INDEX — the dispatcher
    has no rank of its own)."""
    from horovod_tpu.testing import faults

    rank = int(shard[0]) if shard else 0
    return faults.data_fault_ms("dataslow", epoch=epoch, rank=rank)


# --- CLI ---------------------------------------------------------------------


def serve(args) -> int:
    svc = DataService(
        args.dir, host=args.host, port=args.port,
        metrics_port=args.metrics_port,
    ).start()
    print(
        f"hvt-data: serving on {svc.address} "
        f"(journal {svc.journal_path}"
        + (f", metrics :{svc.metrics_port}" if svc.metrics_port is not None
           else "")
        + ")",
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        svc.stop()
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="hvt-data",
        description="fault-tolerant distributed data service dispatcher",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser(
        "serve", help="run the dispatcher daemon (foreground)"
    )
    sp.add_argument("--dir", required=True,
                    help="journal/state directory (restart with the same "
                    "dir to adopt in-flight jobs)")
    sp.add_argument("--host", default=None)
    sp.add_argument("--port", type=int, default=0,
                    help="bind port (0 = ephemeral, printed on start)")
    sp.add_argument("--metrics-port", type=int, default=None,
                    help="serve GET /metrics (hvt_data_* series) here")
    args = p.parse_args(argv)
    return serve(args)


def cli() -> None:
    sys.exit(main())


if __name__ == "__main__":
    cli()
